"""raylint phase 1: the project index.

Single-pass per-file visitors (RL001-RL008) cannot see the bug classes the
runtime actually grew: params baked into jitted executables because a traced
function read ``self.params`` at trace time (the PR 7 hot-swap bug), lock
cycles that span ``llm/engine.py`` → ``llm/prefix_cache.py`` →
``llm/cache.py``, blocking device syncs under locks a watchdog thread also
wants, and metric/event name drift between code, registries and docs.

This module builds the whole-program index those rules need:

* a **per-module symbol table** — imports (absolute and relative),
  module-level functions/classes, module globals with a coarse mutability
  kind (``lock`` for ``threading.Lock()``-style bindings);
* a **per-class attribute table** — every ``self.<attr> = ...`` with where
  it was assigned (``__init__`` vs elsewhere) and a coarse kind
  (``static`` literal config / ``mutable`` array-dict-list state /
  ``unknown``), plus ``attr → project class`` resolution from constructor
  calls, annotations, and constructor *call sites* in other modules
  (``EngineWatchdog(self, ...)`` inside an ``LLMEngine`` method binds the
  watchdog's ``engine`` attribute to ``LLMEngine``);
* a **jit registry** — every function handed to ``jax.jit``/``jit``/
  ``pjit``/``shard_map`` via decorator, ``self._step = jax.jit(self._fn)``
  assignment, inline call, or a ``functools.partial`` wrapper, with its
  ``static_argnums``/``static_argnames``;
* **per-function acquired-lock sets** — every ``with <lock>:`` /
  ``.acquire()``, resolvable to a global owner node (``LLMEngine._lock``,
  not ``self._lock``), whether the acquire is bounded (``timeout=`` /
  non-blocking — a bounded acquire cannot deadlock), and which locks were
  held at every call site and blocking-operation site;
* **thread targets** — functions handed to ``threading.Thread(target=...)``
  (the roots of the daemon-reachability closure RL011 uses), including
  ``target=lambda: self._loop()`` bodies, plus executor ``.submit()``
  hand-offs (``exec_submits``) — together the spawn sites RL017's
  thread-root model is built from;
* **shared-state access sites** — every ``self.<attr>`` / annotated-param
  ``state.<attr>`` read, store, aug-store and mutating method call, and
  every module-global (``_underscore``/``UPPER``) name access, each with
  the locks held at the site (``attr_accesses``/``name_accesses``) — the
  raw material of RL017's guarded-by inference;
* **wire-protocol sites** — message kinds produced (a ``("kind", ...)``
  tuple literal reaching ``send``/``send_raw``/``conn_send``/``_send``,
  directly or through one local/ternary hop) and message kinds handled
  (``kind == "lit"`` comparisons on recv-rooted values) for RL019's
  drift check;
* **emitted observability names** — string literals passed to
  ``events.record``/``events.emit`` and to the ``Counter``/``Gauge``/
  ``Histogram`` constructors, declared ``METRIC_NAMES``/``EVENT_NAMES``
  registries, ``LOCK_ORDER`` declarations, ``ray_tpu_``-prefixed metric
  references inside string literals (grafana/SLO PromQL), and backticked
  names from the repo's observability docs (``DOC_FILES``);
* **mesh/SPMD sites** — mesh constructions and the local names bound to
  them, ``shard_map``/``pjit``/``pmap`` sites with their ``mesh``/
  ``in_specs``/``out_specs``/``in_shardings``/``out_shardings``
  expressions (composition forms like ``jax.jit(shard_map(f, ...))``
  merge onto the inner target), ``PartitionSpec`` literals, collective
  calls with their ``axis_name`` operands, ``pl.pallas_call`` contracts
  (grid rank, BlockSpec block shapes, index_map arity, ``interpret=``
  gating), directly-bound ``device_put``/``global_put`` placements,
  ``make_async_remote_copy`` handle bindings, module-level string-tuple
  globals (axis-name tables like ``AXES``) and ``INTERPRET_ONLY``
  declarations — the raw material of the RL020-RL024 mesh/sharding
  phase (``spmd.py``).

Everything here is a *documented heuristic* over the AST — no imports are
executed, and unresolvable dynamic constructs are skipped
(under-approximation: a rule can miss, it must not invent). Phase 2 lives
in ``rules.py`` (RL009-RL012), which consumes :class:`ProjectIndex`
through the transitive queries at the bottom of this file.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from ray_tpu._lint.core import FileContext

# anchored on a word start so 'clock'/'block'/'unlock' don't match (kept in
# sync with RL005's per-class heuristic)
LOCK_ATTR_RE = re.compile(r"(?:^|_)(lock|rlock|mutex|cv|cond)s?$", re.I)

_JIT_WRAPPERS = {"jit", "pjit", "shard_map", "pmap"}

#: attribute / parameter names that mean "model state", not config — the
#: PR 7 bug class is exactly a traced function reading one of these
MUTABLE_STATE_NAMES = {"params", "weights", "buffers", "variables", "opt_state"}

#: constructors whose result is array data (state, never static config)
_ARRAY_CTORS = {"zeros", "ones", "empty", "full", "array", "asarray", "arange"}

_STATIC_ANNOTATIONS = {"int", "str", "bool", "float", "tuple"}
_MUTABLE_ANNOTATIONS = {"dict", "list", "set", "bytearray", "ndarray", "array"}

#: blocking operations for RL011: device syncs, unbounded queue/future
#: waits and network IO — anything that can park a thread indefinitely
#: while it holds a lock
_BLOCKING_CALLS = {
    "jax.device_get": "device sync",
    "jax.device_put": "device transfer",
    "jax.block_until_ready": "device sync",
    "socket.create_connection": "network IO",
    "urllib.request.urlopen": "network IO",
    "requests.get": "network IO",
    "requests.post": "network IO",
    "requests.request": "network IO",
}

# safe_counter is util.metrics' lazy-Counter helper (drop counters built
# off the hot path): it constructs and registers a Counter, so a call to
# it IS a metric export for RL012 purposes
_METRIC_CTORS = {"Counter", "Gauge", "Histogram", "safe_counter"}

#: mutating container/queue methods: a call through an attribute chain
#: ending in one of these WRITES the state the chain names (RL017's
#: access-kind classification; dict.get/list indexing stay reads)
MUTATING_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert",
    "add", "discard", "remove", "pop", "popleft", "popitem",
    "clear", "update", "setdefault", "put", "put_nowait",
}

#: names that look like module-global state (the repo's idiom is
#: ``_underscore`` privates and ``UPPER`` constants); only these are
#: recorded as name accesses to bound the index size
_GLOBALISH_RE = re.compile(r"^(_[A-Za-z]|[A-Z][A-Z0-9_]*$)")

#: executor receivers whose ``.submit(fn, ...)`` runs ``fn`` on another
#: thread (RL017 thread roots)
_EXECUTOR_RECV_RE = re.compile(r"(pool|executor)s?$", re.I)

#: wire send functions; the message argument position is 1 for
#: ``conn_send(conn, msg)`` / ``_enqueue_send(wh, msg)`` and 0 otherwise
_SEND_FUNCS = {"send": 0, "send_raw": 0, "conn_send": 1, "_send": 0, "_enqueue_send": 1}

#: collective primitives → positional index of their ``axis_name``
#: operand (the ``jax.lax`` spellings plus the jax_compat shims RL020
#: must see through)
_COLLECTIVE_AXIS_POS = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "all_gather": 1, "psum_scatter": 1, "all_to_all": 1,
    "axis_index": 0, "axis_size": 0,
}

#: receivers a collective chain may hang off (``jax.lax.psum`` /
#: ``lax.psum`` / the jax_compat shim); a bare imported name is also
#: accepted. Anything else (``obj.all_gather(...)``) is some project
#: method, not a collective.
_COLLECTIVE_BASES = {"jax", "lax", "jax_compat", "compat"}

#: mesh-constructing calls: ``jax.sharding.Mesh`` itself plus the repo's
#: factory idiom (``make_mesh`` / ``make_multislice_mesh``)
_MESH_CTOR_RE = re.compile(r"^(Mesh|make_\w*mesh)$")

#: repo docs that count as observability-name documentation for RL012
DOC_FILES = ("OBSERVABILITY.md", "RESILIENCE.md")

#: module basenames whose string literals are dashboard/alert row sources —
#: a ``ray_tpu_<metric>`` token there is a PromQL reference RL012 checks
#: against the exported names. Elsewhere the prefix is overwhelmingly a
#: path/tempdir name, not a query.
PROMQL_SOURCE_MODULES = ("grafana", "slo", "dashboard")

_DOC_NAME_RE = re.compile(r"`([A-Za-z_][A-Za-z0-9_.*{}]*)`")
_PROM_REF_RE = re.compile(r"ray_tpu_([a-z][a-z0-9_]*)")


def _is_head_subscript(expr: ast.AST) -> bool:
    """``<name>[0]`` — the message-kind projection (RL019)."""
    return (
        isinstance(expr, ast.Subscript)
        and isinstance(expr.value, ast.Name)
        and isinstance(expr.slice, ast.Constant)
        and expr.slice.value == 0
    )


def dotted_parts(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """``('self', 'pool', '_lock')`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _const_kind(node: ast.AST) -> Optional[str]:
    """'static' for literal config values, 'mutable' for container/array
    displays, None when the expression says nothing."""
    if isinstance(node, ast.Constant):
        # None is a placeholder ("filled in later"), not config evidence
        return None if node.value is None else "static"
    if isinstance(node, ast.Tuple):
        if all(_const_kind(e) == "static" for e in node.elts):
            return "static"
        return None
    if isinstance(
        node, (ast.Dict, ast.List, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
    ):
        return "mutable"
    if isinstance(node, ast.Call):
        d = dotted_parts(node.func)
        if d and (d[-1] in _ARRAY_CTORS or d[-1] in ("dict", "list", "set")):
            return "mutable"
    return None


def _annotation_kind(ann: Optional[ast.AST]) -> Optional[str]:
    if ann is None:
        return None
    if isinstance(ann, ast.Subscript):  # dict[...] / list[...] / Optional[...]
        d = dotted_parts(ann.value)
    else:
        d = dotted_parts(ann)
    name = d[-1] if d else (ann.value if isinstance(ann, ast.Constant) else None)
    if not isinstance(name, str):
        return None
    low = name.lower()
    if low in _STATIC_ANNOTATIONS:
        return "static"
    if low in _MUTABLE_ANNOTATIONS:
        return "mutable"
    return None


@dataclasses.dataclass
class LockAcq:
    """One lock acquisition: raw expression chain + anchor; the global node
    key is resolved lazily via ``ProjectIndex.lock_key``."""

    chain: Tuple[str, ...]
    node: ast.AST
    bounded: bool           # timeout= / non-blocking — cannot deadlock
    via_with: bool
    held: Tuple[Tuple[str, ...], ...] = ()   # chains held when acquiring


@dataclasses.dataclass
class CallSite:
    chain: Tuple[str, ...]
    node: ast.Call
    held: Tuple[Tuple[str, ...], ...]   # lock chains held at this call
    #: like ``held`` but ALSO counting linear ``.acquire()``/``.release()``
    #: bracketing (try/finally idiom) — used by RL017's guarded-by
    #: inference only, so RL010/RL011 edge behavior is unchanged
    held_rt: Tuple[Tuple[str, ...], ...] = ()


@dataclasses.dataclass
class BlockOp:
    label: str
    kind: str
    node: ast.AST
    held: Tuple[Tuple[str, ...], ...]


@dataclasses.dataclass
class JitSite:
    """One ``jax.jit``/``pjit``/``shard_map`` wrapping."""

    target_chain: Optional[Tuple[str, ...]]  # the function being traced
    node: ast.AST                            # anchor for diagnostics
    wrapper: str                             # jit / pjit / shard_map
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    #: positions whose input buffer XLA invalidates (RL013's bug class);
    #: indices are in the traced function's parameter space, which for the
    #: repo's bound-method wrappings equals the call-site arg position
    donate_argnums: Tuple[int, ...] = ()
    decorator_of: Optional[str] = None       # FuncInfo key when via decorator
    # -- mesh/SPMD fields (RL020/RL021/RL024); None/() when not spelled --
    mesh_expr: Optional[ast.AST] = None      # mesh= kwarg / positional
    in_specs: Optional[ast.AST] = None
    out_specs: Optional[ast.AST] = None
    in_shardings: Optional[ast.AST] = None
    out_shardings: Optional[ast.AST] = None
    axis_name: Tuple[str, ...] = ()          # pmap axis binding(s)
    #: the inner wrapper when this is a composition form
    #: (``jax.jit(shard_map(f, ...))`` → wrapper='jit',
    #: composed_with='shard_map', target/specs merged onto f)
    composed_with: Optional[str] = None
    #: positional / keyword args pre-bound by a functools.partial target
    #: (shift the traced function's visible parameter space)
    partial_pos: int = 0
    partial_kw: Tuple[str, ...] = ()

    def wrappers(self) -> set:
        """Both wrapper levels of a composition form."""
        out = {self.wrapper}
        if self.composed_with is not None:
            out.add(self.composed_with)
        return out


@dataclasses.dataclass
class EmitSite:
    name: str
    kind: str                # 'event' | 'metric'
    node: ast.AST


@dataclasses.dataclass
class AttrAccess:
    """One shared-state access site (RL017): an attribute chain rooted at
    ``self`` (alias-normalized) or at an annotated parameter."""

    chain: Tuple[str, ...]
    node: ast.AST
    kind: str                 # 'read' | 'store' | 'aug' | 'mutate'
    held: Tuple[Tuple[str, ...], ...]   # lock chains held (incl. acquire())
    const_store: bool = False  # a plain store of a literal (atomic flag)
    #: innermost enclosing NESTED def name, if any — the scanner models a
    #: nested body at its def site, so the locks its LOCAL CALL SITES
    #: hold are credited back by the thread model (``_take`` defined
    #: before a ``with cv:`` but only called inside it)
    nested: Optional[str] = None


@dataclasses.dataclass
class NameAccess:
    """One module-global access site (RL017); only ``_underscore``/``UPPER``
    names are recorded (the repo's global idiom — see _GLOBALISH_RE)."""

    name: str
    node: ast.AST
    kind: str                 # 'read' | 'store' | 'aug' | 'mutate'
    held: Tuple[Tuple[str, ...], ...]


@dataclasses.dataclass
class MsgCompare:
    """One ``<recv-rooted> == "kind"`` comparison (RL019 handler site).
    ``root`` is ``"recv"`` when the compared value is recv-rooted inside
    this function, or ``("msg", param)`` / ``("kind", param)`` when it
    derives from a parameter — promoted to handled when a caller passes a
    recv-rooted message / kind value at that position."""

    kind: str
    node: ast.AST
    root: object


@dataclasses.dataclass
class CollectiveSite:
    """One collective call (RL020): op name plus its axis operand —
    literal axis names, or the enclosing def's parameter it came from.
    Sites whose axis operand is neither are not recorded (a rule can
    miss, it must not invent)."""

    op: str
    axes: Tuple[str, ...]             # literal axis names; () when a param
    axis_param: Optional[str]         # parameter name carrying the axis
    node: ast.Call


@dataclasses.dataclass
class MeshBind:
    """``mesh = Mesh(...)`` / ``mesh = make_mesh(...)`` — names bound to
    a mesh construction in this scope (RL021's axis-universe anchor)."""

    names: Tuple[str, ...]
    ctor_chain: Tuple[str, ...]
    node: ast.Call


@dataclasses.dataclass
class SpecSite:
    """One ``P(...)`` / ``PartitionSpec(...)`` literal. ``entries`` holds
    a str per literal axis, a tuple of strs per multi-axis dim, None per
    replicated dim, ``"?"`` for dynamic entries and ``"*"`` for starred
    splats (rank unknowable)."""

    entries: Tuple[object, ...]
    node: ast.Call


@dataclasses.dataclass
class NamedShardingSite:
    """``NamedSharding(mesh, P(...))`` — and the repo's ``constrain(x,
    mesh, P(...))`` helper, which carries the same mesh/spec pairing."""

    mesh_chain: Optional[Tuple[str, ...]]
    spec: Optional[ast.Call]          # the P(...) literal, when spelled
    node: ast.Call


@dataclasses.dataclass
class BlockSpecInfo:
    """One ``pl.BlockSpec``: block shape (ints where literal, None for
    squeezed dims, ``"?"`` where dynamic) and the index_map lambda's
    arity when spelled inline."""

    block_shape: Optional[Tuple[object, ...]]
    index_map_arity: Optional[int]
    node: ast.AST
    role: str = "in"       # 'in' | 'out' — which spec list it came from


@dataclasses.dataclass
class PallasSite:
    """One ``pl.pallas_call`` with everything RL022 checks statically."""

    kernel_chain: Optional[Tuple[str, ...]]   # partial-unwrapped kernel fn
    grid_rank: Optional[int]
    num_scalar_prefetch: int
    scalar_grid: bool                 # grid came via PrefetchScalarGridSpec
    block_specs: Tuple[BlockSpecInfo, ...]
    interpret: str                    # 'absent' | 'true' | 'false' | 'dynamic'
    interpret_chain: Optional[Tuple[str, ...]]  # gate-call chain when dynamic
    out_shape_dims: Optional[Tuple[int, ...]]   # literal ShapeDtypeStruct dims
    node: ast.Call


@dataclasses.dataclass
class PlacementSite:
    """One directly-bound ``device_put`` / ``global_put`` (RL021's rank
    check + RL024's drift source). ``sharding`` classifies the second
    operand: 'absent' (committed to the default single device), 'named',
    'single' (explicit SingleDeviceSharding) or 'other'."""

    fn: str
    sharding: str
    sharding_node: Optional[ast.AST]
    spec_rank: Optional[int]          # P(...) rank inside a NamedSharding arg
    operand_rank: Optional[int]       # literal array-ctor rank of operand 0
    bound_names: Tuple[str, ...]
    node: ast.Call


class FuncInfo:
    """Everything the cross-module rules need to know about one def (or
    the module top-level scope, ``qualname == '<module>'``). The scan
    DESCENDS into nested defs — a closure inside a traced function runs
    at trace time, so its reads/calls belong to the enclosing scope."""

    def __init__(self, node, ctx: FileContext, module: str, cls: Optional["ClassInfo"]):
        self.node = node
        self.ctx = ctx
        self.module = module
        self.cls = cls
        self.name = getattr(node, "name", "<module>")
        self.qualname = (
            ctx.qualname(node) if not isinstance(node, ast.Module) else "<module>"
        )
        self.self_name: Optional[str] = None
        args = getattr(node, "args", None)
        if cls is not None and args is not None and args.args and not any(
            isinstance(d, ast.Name) and d.id == "staticmethod"
            for d in getattr(node, "decorator_list", [])
        ):
            self.self_name = args.args[0].arg
        self.acquisitions: List[LockAcq] = []
        self.calls: List[CallSite] = []
        self.blocking: List[BlockOp] = []
        self.self_reads: List[Tuple[str, ast.AST]] = []   # self.<attr> loads
        self.jit_sites: List[JitSite] = []
        self.thread_targets: List[Tuple[Tuple[str, ...], bool]] = []
        self.exec_submits: List[Tuple[str, ...]] = []   # executor .submit(fn)
        # RL017 raw material (see AttrAccess/NameAccess)
        self.attr_accesses: List[AttrAccess] = []
        self.name_accesses: List[NameAccess] = []
        self.global_decls: set = set()        # names in `global` statements
        self.param_names: set = (
            {a.arg for a in args.args + args.kwonlyargs} if args is not None else set()
        )
        #: param name -> (module, class) from annotations (finalize pass)
        self.param_classes: dict[str, Tuple[str, str]] = {}
        # RL019 raw material
        self.msg_sends: List[Tuple[str, ast.AST]] = []
        #: sends whose tuple head is one of THIS function's parameters —
        #: the kind arrives from callers (``_broadcast_rendezvous(msg_kind,
        #: ...)``); promoted one call level by the rule
        self.msg_param_sends: List[Tuple[str, ast.AST]] = []
        self.msg_compares: List[MsgCompare] = []
        self.recv_names: set = set()          # locals holding a recv'd message
        self.kindvar_names: set = set()       # locals holding msg[0]
        # mesh/SPMD raw material (RL020-RL024 — consumed by spmd.py)
        self.collectives: List[CollectiveSite] = []
        self.mesh_binds: List[MeshBind] = []
        self.spec_sites: List[SpecSite] = []
        self.spec_locals: dict[str, ast.Call] = {}  # name -> P(...) literal
        self.named_shardings: List[NamedShardingSite] = []
        self.named_sharding_locals: set = set()     # names bound to NamedSharding
        self.pallas_sites: List[PallasSite] = []
        self.placements: List[PlacementSite] = []
        self.dma_binds: List[Tuple[str, ast.Call]] = []  # async-remote-copy handles

    @property
    def key(self) -> str:
        return f"{self.module}:{self.qualname}"

    def display(self) -> str:
        return f"{self.ctx.display_path}:{self.qualname}"


class ClassInfo:
    def __init__(self, node: ast.ClassDef, ctx: FileContext, module: str):
        self.node = node
        self.ctx = ctx
        self.module = module
        self.name = node.name
        self.methods: dict[str, FuncInfo] = {}
        # attr -> list of (in_init, kind-or-None, value node-or-None)
        self.attr_assigns: dict[str, list] = {}
        # attr -> annotation source text (from `self.x: T = ...` sites)
        self.attr_annotations: dict[str, str] = {}
        # attr -> (module, class) of a resolved project class
        self.attr_classes: dict[str, Tuple[str, str]] = {}
        # __init__ param name -> coarse kind from annotation/default
        self.init_params: dict[str, Optional[str]] = {}
        # attr -> the __init__ param it was assigned from
        self.attr_from_param: dict[str, str] = {}
        # __init__ param -> (module, class) from annotations + call sites
        self.param_classes: dict[str, Tuple[str, str]] = {}

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module, self.name)

    def attr_kind(self, attr: str) -> str:
        """'static' | 'mutable' | 'unknown' — the RL009 classification.

        mutable wins: array/container evidence, a ``params``-ish name, a
        mutable annotation on the source parameter, or any reassignment
        outside ``__init__`` (here or cross-module) marks the attribute
        as state a traced read would bake stale. 'unknown' does NOT fire
        — the rule under-approximates rather than guessing."""
        kinds = set()
        for in_init, kind, _node in self.attr_assigns.get(attr, []):
            if kind in ("static", "mutable"):
                kinds.add(kind)
            if not in_init and kind != "jit_wrapper":
                kinds.add("mutable")  # reassigned after construction
        if attr in MUTABLE_STATE_NAMES:
            kinds.add("mutable")
        src = self.attr_from_param.get(attr)
        if src is not None:
            ann = self.init_params.get(src)
            if ann:
                kinds.add(ann)
            if src in MUTABLE_STATE_NAMES:
                kinds.add("mutable")
        if "mutable" in kinds:
            return "mutable"
        if "static" in kinds:
            return "static"
        return "unknown"


class ModuleInfo:
    def __init__(self, ctx: FileContext, module: str):
        self.ctx = ctx
        self.module = module
        self.imports: dict[str, str] = {}      # local name -> dotted target
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, FuncInfo] = {}   # module-level defs
        self.globals: dict[str, str] = {}      # name -> kind (incl. 'lock')
        self.registries: dict[str, Tuple[list, ast.AST]] = {}
        self.lock_orders: List[Tuple[list, ast.AST]] = []
        self.lockfree: List[Tuple[list, ast.AST]] = []   # RL017 declarations
        self.interpret_only: List[Tuple[list, ast.AST]] = []  # RL022 declarations
        #: every module-level all-string tuple/list global — the axis-name
        #: tables (parallel/mesh.py's AXES) RL021 resolves ``axis_names=``
        #: defaults through, import-following included
        self.str_tuples: dict[str, Tuple[str, ...]] = {}
        self.string_prom_refs: List[Tuple[str, ast.AST]] = []
        self.scope: Optional[FuncInfo] = None  # module top-level pseudo-func


def module_name_for(display_path: str) -> str:
    p = display_path[:-3] if display_path.endswith(".py") else display_path
    mod = p.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


# --------------------------------------------------------------- file scan


class _FunctionScanner(ast.NodeVisitor):
    """One walk per scope: lock nesting, call sites, blocking ops, self
    reads, jit sites, thread targets. Descends into nested defs (they are
    part of the enclosing scope's trace-/run-time behavior) but not into
    sibling top-level defs when scanning the module scope."""

    def __init__(self, info: FuncInfo, index: "ProjectIndex"):
        self.info = info
        self.index = index
        self.held: list[Tuple[str, ...]] = []
        # linear .acquire()/.release() bracketing (try/finally idiom): a
        # second stack layered on `held` for the runtime-access records
        # only — the approximation (source order stands in for control
        # flow) is fine for RL017's guarded-by inference but must not
        # perturb RL010/RL011's with-nesting edges
        self.acq_held: list[Tuple[str, ...]] = []
        self.self_aliases = {info.self_name} if info.self_name else set()
        # `sched = self.scheduler` — local handles onto member objects;
        # calls through them resolve like the spelled-out attribute chain
        self.attr_aliases: dict[str, Tuple[str, ...]] = {}
        # `msg = ("task_done", p) if one else ("tasks_done_batch", b)` —
        # locals holding kind-headed wire tuples (RL019 send extraction)
        self.tuple_kind_locals: dict[str, Tuple[str, ...]] = {}
        # `grid = (bh, seq // bq, seq // bk)` — locals bound to tuple
        # literals, by RANK only (RL022 resolves `grid=grid` through it)
        self.tuple_rank_locals: dict[str, int] = {}
        # `grid_spec = pltpu.PrefetchScalarGridSpec(...)` — locals bound
        # to *GridSpec ctors (RL022 resolves `grid_spec=grid_spec`)
        self.gridspec_locals: dict[str, ast.Call] = {}
        self.nested_defs: list[str] = []  # names of enclosing nested defs
        self.root = info.node
        self.module_scope = isinstance(info.node, ast.Module)

    def _held_rt(self) -> Tuple[Tuple[str, ...], ...]:
        return tuple(self.held) + tuple(self.acq_held)

    # -- helpers --

    def _is_lockish(self, chain: Tuple[str, ...]) -> bool:
        """Lock-ish by NAME (*_lock/mutex/cv/...), or by CONSTRUCTOR for
        self-attrs the class table shows assigned from threading.Lock()
        and friends — PR 14 named its window-build serializer
        ``_submit_send`` (what it serializes, not what it is), and the
        lock graph must still see it (methods scan after __init__ in
        source order, so the ctor evidence is normally present)."""
        if LOCK_ATTR_RE.search(chain[-1]):
            return True
        cls = self.info.cls
        if cls is None or len(chain) < 2:
            return False
        norm = self._self_chain(chain)
        if norm is None or len(norm) != 2:
            return False
        for _in_init, _k, value in cls.attr_assigns.get(norm[1], []):
            if isinstance(value, ast.Call):
                d = dotted_parts(value.func)
                if d and d[-1] in ("Lock", "RLock", "Condition", "Semaphore"):
                    return True
        return False

    def _self_chain(self, chain: Tuple[str, ...]) -> Optional[Tuple[str, ...]]:
        """Normalize an alias-rooted chain (``runner.arch`` after
        ``runner = self``, ``sched.admit`` after ``sched =
        self.scheduler``) to its ``('self', ...)`` spelling; None when
        not self-rooted."""
        if not chain:
            return None
        if chain[0] in self.self_aliases:
            return ("self",) + chain[1:]
        alias = self.attr_aliases.get(chain[0])
        if alias is not None:
            return alias + chain[1:]
        return None

    def _norm(self, chain: Tuple[str, ...]) -> Tuple[str, ...]:
        """Chain as stored: alias-resolved, rooted at the REAL self param
        name so ``resolve_call``/``lock_key`` anchor it."""
        norm = self._self_chain(chain)
        if norm is None:
            return chain
        root = self.info.self_name or "self"
        return (root,) + norm[1:]

    def _access_chain(self, chain: Tuple[str, ...]) -> Optional[Tuple[str, ...]]:
        """Normalize a chain to a recordable shared-state access: rooted at
        the real self name, or at a parameter (``state.reply_buf`` in a
        ``def f(state: WorkerState)``); None for locals/imports."""
        if not chain or len(chain) < 2:
            return None
        norm = self._norm(chain)
        root = norm[0]
        info = self.info
        if info.self_name is not None and root == info.self_name:
            return norm
        if root in info.param_names and root != info.self_name:
            return norm
        return None

    def _record_access(
        self, chain: Tuple[str, ...], node: ast.AST, kind: str,
        const_store: bool = False,
    ) -> None:
        norm = self._access_chain(chain)
        if norm is not None:
            self.info.attr_accesses.append(
                AttrAccess(
                    chain=norm, node=node, kind=kind, held=self._held_rt(),
                    const_store=const_store,
                    nested=self.nested_defs[-1] if self.nested_defs else None,
                )
            )
        elif len(chain) == 1 and _GLOBALISH_RE.match(chain[0]):
            self.info.name_accesses.append(
                NameAccess(
                    name=chain[0], node=node, kind=kind, held=self._held_rt()
                )
            )

    def _wire_kinds(self, expr: ast.AST) -> Tuple[str, ...]:
        """Message kinds an expression can be: a kind-headed tuple literal,
        a ternary of those, or a local bound to one (RL019 send sites)."""
        if isinstance(expr, ast.Tuple) and expr.elts:
            h = expr.elts[0]
            if isinstance(h, ast.Constant) and isinstance(h.value, str):
                return (h.value,)
            return ()
        if isinstance(expr, ast.IfExp):
            return self._wire_kinds(expr.body) + self._wire_kinds(expr.orelse)
        if isinstance(expr, ast.Name):
            return self.tuple_kind_locals.get(expr.id, ())
        return ()

    # -- structure --

    def visit_FunctionDef(self, node):
        if node is self.root:
            self.generic_visit(node)
        elif not self.module_scope:
            self.nested_defs.append(node.name)
            try:
                self.generic_visit(node)
            finally:
                self.nested_defs.pop()
        # module scope skips top-level defs: they get their own FuncInfo

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        pass  # class bodies are scanned via their methods' FuncInfos

    def visit_Assign(self, node):
        v = node.value
        if isinstance(v, ast.Name) and v.id in self.self_aliases:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.self_aliases.add(tgt.id)
        vchain = dotted_parts(v)
        if vchain is not None and len(vchain) == 2:
            vnorm = self._self_chain(vchain)
            if vnorm is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.attr_aliases[tgt.id] = vnorm
        # RL019 provenance: `msg = conn.recv()` / `k, p = conn.recv()` /
        # `kind = msg[0]` / a local bound to a kind-headed wire tuple
        if isinstance(v, ast.Call):
            c = dotted_parts(v.func)
            if c and c[-1] in ("recv", "read_available"):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.info.recv_names.add(tgt.id)
                    elif isinstance(tgt, ast.Tuple) and tgt.elts and isinstance(
                        tgt.elts[0], ast.Name
                    ):
                        self.info.kindvar_names.add(tgt.elts[0].id)
        elif _is_head_subscript(v):
            base = v.value.id
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if base in self.info.recv_names:
                    self.info.kindvar_names.add(tgt.id)
                elif base in self.info.param_names:
                    self.tuple_kind_locals.pop(tgt.id, None)
                    self._param_kindvars()[tgt.id] = base
        kinds = self._wire_kinds(v)
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if kinds:
                    self.tuple_kind_locals[tgt.id] = kinds
                else:
                    # rebinding to a non-kind value invalidates the local:
                    # a later send of it must not report a phantom kind
                    self.tuple_kind_locals.pop(tgt.id, None)
                if isinstance(v, (ast.Tuple, ast.List)):
                    self.tuple_rank_locals[tgt.id] = len(v.elts)
                else:
                    self.tuple_rank_locals.pop(tgt.id, None)
        self._scan_spmd_assign(node)
        for tgt in node.targets:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                for elt in tgt.elts:
                    self._record_attr_assign(elt, None)
            elif isinstance(tgt, ast.Subscript):
                # `self._pending[seq] = slot` / `_rings[id(r)] = r` mutate
                # the container the base chain names
                bchain = dotted_parts(tgt.value)
                if bchain:
                    self._record_access(self._norm(bchain), node, "mutate")
            else:
                self._record_attr_assign(tgt, v)
                if isinstance(tgt, ast.Name) and _GLOBALISH_RE.match(tgt.id):
                    self.info.name_accesses.append(
                        NameAccess(tgt.id, node, "store", self._held_rt())
                    )
        self.generic_visit(node)
        # placements are recorded by visit_Call during the generic visit;
        # a directly-bound one gets its target names here (RL024 tracks
        # the bound value into later jitted calls)
        if isinstance(v, ast.Call):
            names = tuple(t.id for t in node.targets if isinstance(t, ast.Name))
            if names:
                for p in self.info.placements:
                    if p.node is v:
                        p.bound_names = names

    def _scan_spmd_assign(self, node: ast.Assign) -> None:
        """Mesh/SPMD bindings: mesh ctors, P literals, NamedSharding
        handles and make_async_remote_copy DMA handles bound to names."""
        v = node.value
        if not isinstance(v, ast.Call):
            return
        names = tuple(t.id for t in node.targets if isinstance(t, ast.Name))
        if not names:
            return
        chain = dotted_parts(v.func)
        if not chain:
            return
        last = chain[-1]
        if _MESH_CTOR_RE.match(last):
            self.info.mesh_binds.append(
                MeshBind(names=names, ctor_chain=chain, node=v)
            )
        elif last == "make_async_remote_copy":
            for n in names:
                self.info.dma_binds.append((n, v))
        elif last in ("P", "PartitionSpec"):
            for n in names:
                self.info.spec_locals[n] = v
        elif last == "NamedSharding":
            self.info.named_sharding_locals.update(names)
        elif last.endswith("GridSpec"):
            for n in names:
                self.gridspec_locals[n] = v

    def _param_kindvars(self) -> dict:
        got = getattr(self.info, "_param_kindvars", None)
        if got is None:
            got = self.info._param_kindvars = {}
        return got

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._record_attr_assign(node.target, node.value)
        if isinstance(node.target, ast.Attribute) and self.info.cls is not None:
            chain = dotted_parts(node.target)
            norm = self._self_chain(chain) if chain else None
            if norm is not None and len(norm) == 2:
                try:
                    self.info.cls.attr_annotations.setdefault(
                        norm[1], ast.unparse(node.annotation)
                    )
                except Exception:
                    pass
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._record_attr_assign(node.target, None, record_access=False)
        tgt = node.target
        if isinstance(tgt, ast.Attribute):
            chain = dotted_parts(tgt)
            if chain:
                self._record_access(self._norm(chain), node, "aug")
        elif isinstance(tgt, ast.Name) and _GLOBALISH_RE.match(tgt.id):
            self.info.name_accesses.append(
                NameAccess(tgt.id, node, "aug", self._held_rt())
            )
        elif isinstance(tgt, ast.Subscript):
            bchain = dotted_parts(tgt.value)
            if bchain:
                self._record_access(self._norm(bchain), node, "mutate")
        self.generic_visit(node)

    def visit_Global(self, node):
        self.info.global_decls.update(node.names)

    def visit_For(self, node):
        # `for msg in reader.read_available():` — the loop target is a
        # recv-rooted message (RL019)
        it = node.iter
        rooted = False
        if isinstance(it, ast.Call):
            c = dotted_parts(it.func)
            rooted = bool(c) and c[-1] in ("recv", "read_available")
        elif isinstance(it, ast.Name):
            rooted = it.id in self.info.recv_names
        if rooted and isinstance(node.target, ast.Name):
            self.info.recv_names.add(node.target.id)
        self.generic_visit(node)

    visit_AsyncFor = visit_For

    def visit_Compare(self, node):
        # `<kind-var> == "lit"` / `msg[0] != "lit"` / `kind in ("a", "b")`
        # — RL019 handler sites, counted only for recv-/param-rooted values
        if len(node.ops) == 1 and isinstance(node.ops[0], (ast.Eq, ast.NotEq, ast.In)):
            lits: list[str] = []
            sides = [node.left, node.comparators[0]]
            expr = None
            for s in sides:
                if isinstance(s, ast.Constant) and isinstance(s.value, str):
                    lits.append(s.value)
                elif isinstance(s, ast.Tuple) and isinstance(node.ops[0], ast.In):
                    lits.extend(
                        e.value
                        for e in s.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    )
                else:
                    expr = s
            if lits and expr is not None:
                root = self._kind_root(expr)
                if root is not None:
                    for lit in lits:
                        self.info.msg_compares.append(
                            MsgCompare(kind=lit, node=node, root=root)
                        )
        self.generic_visit(node)

    def _kind_root(self, expr: ast.AST) -> Optional[object]:
        info = self.info
        if _is_head_subscript(expr):
            base = expr.value.id
            if base in info.recv_names:
                return "recv"
            if base in info.param_names and base != info.self_name:
                return ("msg", base)
            return None
        if isinstance(expr, ast.Name):
            if expr.id in info.kindvar_names:
                return "recv"
            pk = getattr(info, "_param_kindvars", None)
            if pk and expr.id in pk:
                return ("msg", pk[expr.id])
        return None

    def visit_Name(self, node):
        if (
            isinstance(node.ctx, ast.Load)
            and _GLOBALISH_RE.match(node.id)
            and node.id not in self.info.param_names
        ):
            self.info.name_accesses.append(
                NameAccess(node.id, node, "read", self._held_rt())
            )

    def _record_attr_assign(
        self, tgt: ast.AST, value: Optional[ast.AST], record_access: bool = True
    ) -> None:
        if not isinstance(tgt, ast.Attribute):
            return  # rebinding a local (even a self-alias) mutates no attr
        chain = dotted_parts(tgt)
        if not chain:
            return
        if record_access:
            self._record_access(
                chain, tgt, "store",
                const_store=isinstance(value, ast.Constant),
            )
        norm = self._self_chain(chain)
        cls = self.info.cls
        if norm is not None and len(norm) == 2 and cls is not None:
            in_init = self.info.name == "__init__"
            kind = _const_kind(value) if value is not None else None
            if value is not None and self.index._jit_site_from_call(value) is not None:
                kind = "jit_wrapper"
            cls.attr_assigns.setdefault(norm[1], []).append((in_init, kind, value))
            if in_init and isinstance(value, ast.Name):
                cls.attr_from_param.setdefault(norm[1], value.id)
            if in_init and isinstance(value, ast.Call):
                # resolved in _finalize: the constructed class may live in
                # a module that has not been scanned yet
                ctor = dotted_parts(value.func)
                if ctor:
                    self.index._deferred_attr_ctors.append(
                        (cls, norm[1], self.info, ctor)
                    )
        elif norm is not None and len(norm) == 3:
            # cross-object mutation: `self.runner.params = ...` marks the
            # attribute mutable on the RESOLVED class (finalize pass)
            self.index._deferred_mutations.append((self.info, norm))

    def visit_With(self, node):
        acquired = 0
        for item in node.items:
            self.visit(item.context_expr)
            chain = dotted_parts(item.context_expr)
            chain = self._norm(chain) if chain else chain
            if chain and self._is_lockish(chain):
                self.info.acquisitions.append(
                    LockAcq(
                        chain=chain, node=node, bounded=False, via_with=True,
                        held=tuple(self.held),
                    )
                )
                self.held.append(chain)
                acquired += 1
        for child in node.body:
            self.visit(child)
        for _ in range(acquired):
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        chain = dotted_parts(node.func)
        chain = self._norm(chain) if chain else chain
        if chain:
            if (
                chain[-1] == "acquire"
                and len(chain) > 1
                and self._is_lockish(chain[:-1])
            ):
                bounded = any(kw.arg == "timeout" for kw in node.keywords)
                if (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value is False
                ):
                    bounded = True
                if any(
                    kw.arg == "blocking"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                    for kw in node.keywords
                ):
                    bounded = True
                if len(node.args) >= 2:
                    bounded = True  # acquire(blocking, timeout)
                self.info.acquisitions.append(
                    LockAcq(
                        chain=chain[:-1], node=node, bounded=bounded,
                        via_with=False, held=tuple(self.held),
                    )
                )
                self.acq_held.append(chain[:-1])
            if (
                chain[-1] == "release"
                and len(chain) > 1
                and self._is_lockish(chain[:-1])
                and chain[:-1] in self.acq_held
            ):
                self.acq_held.remove(chain[:-1])
            if chain[-1] == "Thread":
                target = None
                daemon = False
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = dotted_parts(kw.value)
                        if target is None and isinstance(kw.value, ast.Lambda):
                            # target=lambda: self._loop() — the body call is
                            # the real thread root
                            body = kw.value.body
                            if isinstance(body, ast.Call):
                                target = dotted_parts(body.func)
                        if target is not None:
                            target = self._norm(target)
                    elif kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                        daemon = bool(kw.value.value)
                if target is not None:
                    self.info.thread_targets.append((target, daemon))
            if (
                chain[-1] == "submit"
                and len(chain) > 1
                and _EXECUTOR_RECV_RE.search(chain[-2])
                and node.args
            ):
                t = dotted_parts(node.args[0])
                if t is not None:
                    self.info.exec_submits.append(self._norm(t))
            if chain[-1] == "run_in_executor" and len(node.args) >= 2:
                t = dotted_parts(node.args[1])
                if t is None and isinstance(node.args[1], ast.Call):
                    # functools.partial(fn, ...) — unwrap to fn
                    inner = dotted_parts(node.args[1].func)
                    if inner and inner[-1] == "partial" and node.args[1].args:
                        t = dotted_parts(node.args[1].args[0])
                if t is not None:
                    self.info.exec_submits.append(self._norm(t))
            if chain[-1] in MUTATING_METHODS and len(chain) >= 2:
                self._record_access(chain[:-1], node, "mutate")
            send_arg = _SEND_FUNCS.get(chain[-1])
            if send_arg is not None and len(node.args) > send_arg:
                marg = node.args[send_arg]
                kinds = self._wire_kinds(marg)
                for kind in kinds:
                    self.info.msg_sends.append((kind, node))
                if (
                    not kinds
                    and isinstance(marg, ast.Tuple)
                    and marg.elts
                    and isinstance(marg.elts[0], ast.Name)
                    and marg.elts[0].id in self.info.param_names
                ):
                    self.info.msg_param_sends.append((marg.elts[0].id, node))
            site = self.index._jit_site_from_call(node)
            if site is not None:
                self.info.jit_sites.append(site)
            label = self.index._blocking_label(chain, node)
            if label is not None:
                self.info.blocking.append(
                    BlockOp(
                        label=label[0], kind=label[1], node=node,
                        held=tuple(self.held),
                    )
                )
            emit = self.index._emit_from_call(chain, node, self.info)
            if emit is not None:
                self.index.emits.append((emit, self.info))
            self._scan_spmd_call(chain, node)
            self.info.calls.append(
                CallSite(
                    chain=chain, node=node, held=tuple(self.held),
                    held_rt=self._held_rt(),
                )
            )
        self.generic_visit(node)

    def _scan_spmd_call(self, chain: Tuple[str, ...], node: ast.Call) -> None:
        """Mesh/SPMD call sites: collectives, pallas_call, P literals,
        NamedSharding/constrain pairings, device_put/global_put
        placements (RL020-RL024 raw material)."""
        info = self.info
        last = chain[-1]
        if last in _COLLECTIVE_AXIS_POS and (
            len(chain) == 1 or chain[-2] in _COLLECTIVE_BASES
        ):
            pos = _COLLECTIVE_AXIS_POS[last]
            axis = None
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    axis = kw.value
            if axis is None and len(node.args) > pos:
                axis = node.args[pos]
            if axis is not None:
                axes: Tuple[str, ...] = ()
                param = None
                if isinstance(axis, ast.Constant) and isinstance(axis.value, str):
                    axes = (axis.value,)
                elif isinstance(axis, (ast.Tuple, ast.List)) and axis.elts and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in axis.elts
                ):
                    axes = tuple(e.value for e in axis.elts)
                elif isinstance(axis, ast.Name) and axis.id in info.param_names:
                    param = axis.id
                if axes or param is not None:
                    info.collectives.append(
                        CollectiveSite(op=last, axes=axes, axis_param=param, node=node)
                    )
        elif last == "pallas_call":
            info.pallas_sites.append(
                _pallas_site(node, self.tuple_rank_locals, self.gridspec_locals)
            )
        elif last in ("P", "PartitionSpec"):
            info.spec_sites.append(
                SpecSite(entries=_spec_entries(node), node=node)
            )
        elif last == "NamedSharding" and node.args:
            spec = node.args[1] if len(node.args) >= 2 else None
            info.named_shardings.append(
                NamedShardingSite(
                    mesh_chain=dotted_parts(node.args[0]),
                    spec=spec if _is_spec_call(spec) else None,
                    node=node,
                )
            )
        elif last == "constrain" and len(node.args) >= 3:
            # the repo's `constrain(x, mesh, spec)` sharding-constraint
            # helper carries the same mesh/spec pairing as NamedSharding
            spec = node.args[2]
            info.named_shardings.append(
                NamedShardingSite(
                    mesh_chain=dotted_parts(node.args[1]),
                    spec=spec if _is_spec_call(spec) else None,
                    node=node,
                )
            )
        elif last in ("device_put", "global_put"):
            site = _placement_site(node, last)
            if (
                site.sharding == "other"
                and isinstance(site.sharding_node, ast.Name)
                and site.sharding_node.id in info.named_sharding_locals
            ):
                site.sharding = "named"
            info.placements.append(site)

    def visit_Attribute(self, node):
        if isinstance(node.ctx, ast.Load):
            chain = dotted_parts(node)
            if chain:
                norm = self._self_chain(chain)
                if norm is not None and len(norm) >= 2:
                    self.info.self_reads.append((norm[1], node))
                self._record_access(chain, node, "read")
        self.generic_visit(node)


# --------------------------------------------------------------- the index


class ProjectIndex:
    """Whole-program facts for phase-2 rules. Build once per run via
    :func:`build_index`; every query is read-only."""

    def __init__(
        self, contexts: Sequence[FileContext], display_root: Optional[Path] = None
    ):
        self.modules: dict[str, ModuleInfo] = {}
        self.classes: dict[Tuple[str, str], ClassInfo] = {}
        self.functions: dict[str, FuncInfo] = {}
        self.emits: List[Tuple[EmitSite, FuncInfo]] = []
        self.jit_sites: List[Tuple[JitSite, FuncInfo]] = []
        self.doc_names: set = set()
        self.display_root = display_root
        self._deferred_mutations: list = []
        self._deferred_attr_ctors: list = []
        self._deferred_param_anns: list = []
        self._deferred_func_param_anns: list = []
        self._locks_memo: dict[str, frozenset] = {}
        self._block_memo: dict[str, list] = {}
        for ctx in contexts:
            self._scan_file(ctx)
        self._finalize()
        self._load_docs()

    # -- construction ------------------------------------------------------

    def _scan_file(self, ctx: FileContext) -> None:
        module = module_name_for(ctx.display_path)
        mi = ModuleInfo(ctx, module)
        self.modules[module] = mi
        is_pkg = ctx.display_path.endswith("__init__.py")
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        mi.imports[a.asname] = a.name
                    else:
                        mi.imports[a.name.split(".")[0]] = a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0:
                    base = node.module or ""
                else:
                    parts = module.split(".")
                    keep = max(len(parts) - node.level + (1 if is_pkg else 0), 0)
                    anchor = parts[:keep]
                    base = ".".join(anchor + ([node.module] if node.module else []))
                for a in node.names:
                    mi.imports[a.asname or a.name] = (
                        f"{base}.{a.name}" if base else a.name
                    )
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                self._scan_module_assign(mi, stmt)
            elif isinstance(stmt, ast.ClassDef):
                self._scan_class(mi, stmt)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mi, stmt, cls=None)
        # the module top-level scope as a pseudo-function (module-level
        # jit wrapping, thread spawns, emissions)
        scope = FuncInfo(ctx.tree, ctx, module, cls=None)
        mi.scope = scope
        self.functions[scope.key] = scope
        _FunctionScanner(scope, self).visit(ctx.tree)
        promql_module = module.rsplit(".", 1)[-1] in PROMQL_SOURCE_MODULES
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                text = node.value
                if not (
                    promql_module
                    or "rate(" in text
                    or "histogram_quantile" in text
                ):
                    continue
                for m in _PROM_REF_RE.finditer(text):
                    nxt = text[m.end(): m.end() + 1]
                    # a token flowing into a filename/path is not a query
                    if nxt in (".", "/", "-") or m.group(1).endswith("_"):
                        continue
                    mi.string_prom_refs.append((m.group(1), node))

    def _scan_module_assign(self, mi: ModuleInfo, stmt: ast.Assign) -> None:
        names = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
        if not names:
            return
        v = stmt.value
        kind = _const_kind(v)
        if isinstance(v, ast.Call):
            d = dotted_parts(v.func)
            if d and d[-1] in ("Lock", "RLock", "Condition", "Semaphore"):
                kind = "lock"
            elif d and d[-1] in ("Event", "Queue", "SimpleQueue", "LifoQueue"):
                kind = "sync"  # internally synchronized, not lockable
        for name in names:
            if kind:
                mi.globals[name] = kind
            if name in ("METRIC_NAMES", "EVENT_NAMES") and isinstance(
                v, (ast.Tuple, ast.List, ast.Set)
            ):
                vals = [
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
                mi.registries[name] = (vals, stmt)
            if name == "LOCK_ORDER" and isinstance(v, (ast.Tuple, ast.List)):
                vals = [
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
                mi.lock_orders.append((vals, stmt))
            if name == "LOCKFREE" and isinstance(v, (ast.Tuple, ast.List)):
                vals = [
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
                mi.lockfree.append((vals, stmt))
            if name == "INTERPRET_ONLY" and isinstance(v, (ast.Tuple, ast.List)):
                vals = [
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
                mi.interpret_only.append((vals, stmt))
        if isinstance(v, (ast.Tuple, ast.List)) and v.elts and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in v.elts
        ):
            vals_t = tuple(e.value for e in v.elts)
            for name in names:
                mi.str_tuples[name] = vals_t

    def _scan_class(self, mi: ModuleInfo, node: ast.ClassDef) -> None:
        ci = ClassInfo(node, mi.ctx, mi.module)
        mi.classes[node.name] = ci
        self.classes[ci.key] = ci
        # __init__ scans FIRST regardless of source position: the
        # scanner's ctor-typed lock classification (_is_lockish) reads
        # the attr table mid-scan, and a method defined above __init__
        # must still see `self._submit_send = threading.Lock()` evidence
        methods = [
            s for s in node.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        methods.sort(key=lambda s: s.name != "__init__")
        for stmt in methods:
            self._add_function(mi, stmt, cls=ci)
        init = ci.methods.get("__init__")
        if init is None:
            return
        args = init.node.args
        dmap: dict[str, ast.AST] = {}
        pos_defaults = list(args.defaults)
        for arg, d in zip(args.args[len(args.args) - len(pos_defaults):], pos_defaults):
            dmap[arg.arg] = d
        for arg, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None:
                dmap[arg.arg] = d
        every = (list(args.args) + list(args.kwonlyargs))[1:]
        for a in every:
            kind = _annotation_kind(a.annotation)
            if kind is None and a.arg in dmap:
                dk = _const_kind(dmap[a.arg])
                kind = dk if dk in ("static", "mutable") else None
            ci.init_params[a.arg] = kind
            if a.annotation is not None:
                self._deferred_param_anns.append((ci, a.arg, a.annotation))

    def _add_function(self, mi: ModuleInfo, node, cls: Optional[ClassInfo]) -> None:
        info = FuncInfo(node, mi.ctx, mi.module, cls)
        if cls is not None:
            cls.methods[node.name] = info
        else:
            mi.functions[node.name] = info
        self.functions[info.key] = info
        for dec in node.decorator_list:
            site = self._jit_decorator(dec, info)
            if site is not None:
                self.jit_sites.append((site, info))
        # param annotations resolve to project classes in _finalize (the
        # annotated class may live in a module not yet scanned) — this is
        # what anchors `state.reply_buf` / `ctx._fail_submits()` chains in
        # worker_main-style module functions
        args = getattr(node, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs):
                if a.annotation is not None:
                    self._deferred_func_param_anns.append((info, a.arg, a.annotation))
        _FunctionScanner(info, self).visit(node)

    def _finalize(self) -> None:
        # attr → class from __init__ constructor calls and annotations,
        # deferred past the scan so resolution order cannot depend on the
        # file walk order
        for cls, attr, info, ctor in self._deferred_attr_ctors:
            ck = self._resolve_class_chain(ctor, info)
            if ck is not None:
                cls.attr_classes.setdefault(attr, ck)
        for ci, pname, ann in self._deferred_param_anns:
            mi = self.modules.get(ci.module)
            if mi is None:
                continue
            ck = self._class_from_annotation(ann, mi)
            if ck is not None:
                ci.param_classes.setdefault(pname, ck)
        for info, pname, ann in self._deferred_func_param_anns:
            mi = self.modules.get(info.module)
            if mi is None:
                continue
            ck = self._class_from_annotation(ann, mi)
            if ck is not None:
                info.param_classes.setdefault(pname, ck)
        # ctor-callsite param→class inference; two sweeps so attr_classes
        # resolved in sweep 1 feed argument chains resolved in sweep 2
        for _ in range(2):
            for info in list(self.functions.values()):
                for call in info.calls:
                    target = self._resolve_class_chain(call.chain, info)
                    if target is None:
                        continue
                    ci = self.classes.get(target)
                    if ci is None:
                        continue
                    init = ci.methods.get("__init__")
                    if init is None:
                        continue
                    pos_params = [a.arg for a in init.node.args.args[1:]]
                    bindings: list[Tuple[str, ast.AST]] = []
                    for i, arg in enumerate(call.node.args):
                        if i < len(pos_params):
                            bindings.append((pos_params[i], arg))
                    for kw in call.node.keywords:
                        if kw.arg:
                            bindings.append((kw.arg, kw.value))
                    for pname, expr in bindings:
                        ck = self._class_of_expr(expr, info)
                        if ck is not None:
                            ci.param_classes.setdefault(pname, ck)
            for ci in self.classes.values():
                for attr, pname in ci.attr_from_param.items():
                    if pname in ci.param_classes:
                        ci.attr_classes.setdefault(attr, ci.param_classes[pname])
        # cross-object mutations: self.<x>.<attr> = ... marks <attr>
        # mutable on the resolved class of <x>
        for info, norm in self._deferred_mutations:
            if info.cls is None:
                continue
            ck = info.cls.attr_classes.get(norm[1])
            if ck is None:
                continue
            owner = self.classes.get(ck)
            if owner is not None:
                owner.attr_assigns.setdefault(norm[2], []).append(
                    (False, "mutable", None)
                )
        # jit sites recorded inside function bodies
        for info in self.functions.values():
            for site in info.jit_sites:
                self.jit_sites.append((site, info))

    def _load_docs(self) -> None:
        roots = []
        if self.display_root is not None:
            roots.append(Path(self.display_root))
        else:
            # no explicit repo root (library callers, the self-host test):
            # walk up from the first scanned file to the nearest directory
            # holding any of the observability docs
            for mi in self.modules.values():
                start = Path(mi.ctx.path).resolve().parent
                for d in (start, *start.parents):
                    if any((d / name).is_file() for name in DOC_FILES):
                        roots.append(d)
                        break
                break
        for root in roots:
            for name in DOC_FILES:
                p = root / name
                try:
                    text = p.read_text(encoding="utf-8", errors="replace")
                except OSError:
                    continue
                for m in _DOC_NAME_RE.finditer(text):
                    self.doc_names.add(m.group(1))

    # -- scan-time helpers (called by _FunctionScanner) --------------------

    def _jit_site_from_call(self, node: ast.AST) -> Optional[JitSite]:
        """``jax.jit(fn, ...)`` / ``shard_map(fn, mesh=...)``, unwrapping a
        ``functools.partial(fn, ...)`` first argument and seeing through
        ONE composition level — ``jax.jit(shard_map(f, ...))`` and
        ``shard_map(jax.jit(f), ...)`` — so donation/static/spec facts
        from both wrapper levels merge onto the inner target (the form
        the multi-chip engine will lean on; RL013/RL014 must not go
        silent there)."""
        if not isinstance(node, ast.Call):
            return None
        chain = dotted_parts(node.func)
        if not chain or chain[-1] not in _JIT_WRAPPERS or not node.args:
            return None
        target = node.args[0]
        partial_pos = 0
        partial_kw: Tuple[str, ...] = ()
        inner_site = None
        if isinstance(target, ast.Call):
            inner = dotted_parts(target.func)
            if inner and inner[-1] == "partial" and target.args:
                partial_pos = len(target.args) - 1
                partial_kw = tuple(kw.arg for kw in target.keywords if kw.arg)
                target = target.args[0]
            elif inner and inner[-1] in _JIT_WRAPPERS:
                inner_site = self._jit_site_from_call(target)
        site = JitSite(
            target_chain=dotted_parts(target),
            node=node,
            wrapper=chain[-1],
            static_argnums=_kw_int_tuple(node, "static_argnums"),
            static_argnames=_kw_str_tuple(node, "static_argnames"),
            donate_argnums=_kw_int_tuple(node, "donate_argnums"),
            partial_pos=partial_pos,
            partial_kw=partial_kw,
        )
        _fill_spec_fields(site, node, positional=True)
        if inner_site is not None:
            site.target_chain = inner_site.target_chain
            site.composed_with = inner_site.wrapper
            site.static_argnums = tuple(
                sorted(set(site.static_argnums) | set(inner_site.static_argnums))
            )
            site.static_argnames = tuple(
                sorted(set(site.static_argnames) | set(inner_site.static_argnames))
            )
            site.donate_argnums = tuple(
                sorted(set(site.donate_argnums) | set(inner_site.donate_argnums))
            )
            site.partial_pos = inner_site.partial_pos
            site.partial_kw = inner_site.partial_kw
            for field in (
                "mesh_expr", "in_specs", "out_specs",
                "in_shardings", "out_shardings",
            ):
                if getattr(site, field) is None:
                    setattr(site, field, getattr(inner_site, field))
            if not site.axis_name:
                site.axis_name = inner_site.axis_name
        return site

    def _jit_decorator(self, dec: ast.AST, info: FuncInfo) -> Optional[JitSite]:
        chain = dotted_parts(dec.func if isinstance(dec, ast.Call) else dec)
        if chain and chain[-1] in _JIT_WRAPPERS:
            site = JitSite(
                target_chain=None,
                node=dec,
                wrapper=chain[-1],
                static_argnums=(
                    _kw_int_tuple(dec, "static_argnums")
                    if isinstance(dec, ast.Call) else ()
                ),
                static_argnames=(
                    _kw_str_tuple(dec, "static_argnames")
                    if isinstance(dec, ast.Call) else ()
                ),
                donate_argnums=(
                    _kw_int_tuple(dec, "donate_argnums")
                    if isinstance(dec, ast.Call) else ()
                ),
                decorator_of=info.key,
            )
            if isinstance(dec, ast.Call):
                _fill_spec_fields(site, dec, positional=False)
            return site
        # @partial(jax.jit, static_argnums=...)
        if isinstance(dec, ast.Call) and chain and chain[-1] == "partial" and dec.args:
            inner = dotted_parts(dec.args[0])
            if inner and inner[-1] in _JIT_WRAPPERS:
                site = JitSite(
                    target_chain=None,
                    node=dec,
                    wrapper=inner[-1],
                    static_argnums=_kw_int_tuple(dec, "static_argnums"),
                    static_argnames=_kw_str_tuple(dec, "static_argnames"),
                    donate_argnums=_kw_int_tuple(dec, "donate_argnums"),
                    decorator_of=info.key,
                )
                _fill_spec_fields(site, dec, positional=False)
                return site
        return None

    def _blocking_label(self, chain, node: ast.Call):
        dotted = ".".join(chain)
        if dotted in _BLOCKING_CALLS:
            return dotted, _BLOCKING_CALLS[dotted]
        last = chain[-1]
        if last == "block_until_ready":
            return f"{dotted}()", "device sync"
        if (
            last == "get"
            and len(chain) > 1
            and (
                "queue" in chain[-2].lower()
                or "stream" in chain[-2].lower()
                or chain[-2].lower().endswith("q")
            )
        ):
            # a BLOCKING queue.get() has no positional args — dict.get(key)
            # and queue.get(block, timeout) forms are not unbounded waits
            if not node.args and not any(
                kw.arg == "timeout" for kw in node.keywords
            ):
                return f"{dotted}()", "unbounded queue wait"
            return None
        if last == "result" and not node.args and not any(
            kw.arg == "timeout" for kw in node.keywords
        ):
            return f"{dotted}()", "unbounded future wait"
        return None

    def _emit_from_call(
        self, chain, node: ast.Call, info: FuncInfo
    ) -> Optional[EmitSite]:
        if not node.args or not isinstance(node.args[0], ast.Constant):
            return None
        first = node.args[0].value
        if not isinstance(first, str):
            return None
        last = chain[-1]
        if last in ("record", "emit") and len(chain) > 1 and (
            "events" in chain[-2] or chain[-2] == "_events"
        ):
            return EmitSite(name=first, kind="event", node=node)
        if last in _METRIC_CTORS and len(chain) <= 2:
            mi = self.modules.get(info.module)
            base = chain[0] if len(chain) == 2 else last
            tgt = mi.imports.get(base, "") if mi else ""
            if tgt.startswith("collections") or base == "collections":
                return None  # collections.Counter is not a metric
            return EmitSite(name=first, kind="metric", node=node)
        return None

    # -- resolution --------------------------------------------------------

    def _class_from_annotation(self, ann, mi: ModuleInfo):
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.split(".")[-1].strip()
        else:
            d = dotted_parts(ann)
            name = d[-1] if d else None
        if not name:
            return None
        return self._lookup_class(name, mi)

    def _lookup_class(self, name: str, mi: ModuleInfo):
        if name in mi.classes:
            return mi.classes[name].key
        tgt = mi.imports.get(name)
        if tgt and "." in tgt:
            mod, _, cname = tgt.rpartition(".")
            tmi = self.modules.get(mod)
            if tmi and cname in tmi.classes:
                return tmi.classes[cname].key
            # re-export through a package __init__: unique class name wins
            cands = [c.key for c in self.classes.values() if c.name == cname]
            if len(cands) == 1:
                return cands[0]
        return None

    def _resolve_class_chain(self, chain, info: FuncInfo):
        """A call chain that constructs a project class → its key."""
        if not chain:
            return None
        mi = self.modules.get(info.module)
        if mi is None:
            return None
        if len(chain) == 1:
            return self._lookup_class(chain[0], mi)
        if len(chain) == 2:
            base = mi.imports.get(chain[0])
            if base:
                tmi = self.modules.get(base)
                if tmi and chain[1] in tmi.classes:
                    return tmi.classes[chain[1]].key
        return None

    def _class_of_expr(self, expr: ast.AST, info: FuncInfo):
        chain = dotted_parts(expr)
        if not chain:
            return None
        if info.cls is not None and info.self_name and chain[0] == info.self_name:
            if len(chain) == 1:
                return info.cls.key
            if len(chain) == 2:
                return info.cls.attr_classes.get(chain[1])
        return None

    def lock_key(self, chain: Tuple[str, ...], info: FuncInfo) -> Optional[str]:
        """Resolve an acquisition chain to a global lock node:
        ``('self','_lock')`` in an LLMEngine method → ``LLMEngine._lock``;
        ``('self','pool','_lock')`` → ``KVBlockPool._lock`` via the attr
        table; a module global → ``<module>.<NAME>``. None when the chain
        cannot be anchored to an owner (a local-variable lock)."""
        if not chain:
            return None
        mi = self.modules.get(info.module)
        if info.self_name and chain[0] == info.self_name and info.cls is not None:
            if len(chain) == 2:
                return f"{info.cls.name}.{chain[1]}"
            if len(chain) == 3:
                ck = info.cls.attr_classes.get(chain[1])
                if ck is not None:
                    return f"{ck[1]}.{chain[2]}"
            return f"{info.cls.name}.{'.'.join(chain[1:])}"
        if chain[0] in info.param_classes and len(chain) >= 2:
            # annotated-parameter root: `state.reply_send` in a module
            # function `def f(state: WorkerState)` owns like self chains
            ck = info.param_classes[chain[0]]
            if len(chain) == 2:
                return f"{ck[1]}.{chain[1]}"
            owner = self.classes.get(ck)
            if owner is not None and len(chain) == 3:
                ck2 = owner.attr_classes.get(chain[1])
                if ck2 is not None:
                    return f"{ck2[1]}.{chain[2]}"
            return None
        if len(chain) == 1:
            if mi and mi.globals.get(chain[0]) == "lock":
                return f"{info.module}.{chain[0]}"
            return None
        if mi and chain[0] in mi.imports and len(chain) == 2:
            base = mi.imports[chain[0]]
            tmi = self.modules.get(base)
            if tmi is not None and tmi.globals.get(chain[1]) == "lock":
                return f"{base}.{chain[1]}"
        return None

    def resolve_call(self, info: FuncInfo, chain: Tuple[str, ...]) -> Optional[FuncInfo]:
        """Call chain → callee FuncInfo when it can be anchored: self
        methods (incl. attr-resolved member objects and jit-wrapper
        attributes), module functions, imported project functions, and
        constructor calls (→ ``__init__``)."""
        mi = self.modules.get(info.module)
        if not chain or mi is None:
            return None
        if info.self_name and chain[0] == info.self_name and info.cls is not None:
            if len(chain) == 2:
                m = info.cls.methods.get(chain[1])
                if m is not None:
                    return m
                # self._decode(...) where _decode = jax.jit(self._decode_impl)
                for _in_init, kind, value in info.cls.attr_assigns.get(chain[1], []):
                    if kind == "jit_wrapper" and isinstance(value, ast.Call):
                        site = self._jit_site_from_call(value)
                        if site is not None:
                            init = info.cls.methods.get("__init__")
                            return self.resolve_jit_target(site, init or info)
                return None
            if len(chain) == 3:
                ck = info.cls.attr_classes.get(chain[1])
                if ck is not None:
                    owner = self.classes.get(ck)
                    if owner is not None:
                        return owner.methods.get(chain[2])
            return None
        if chain[0] in info.param_classes and len(chain) in (2, 3):
            # `ctx._fail_submits(...)` / `state.ctx.send_raw(...)` in a
            # module function with annotated params
            owner = self.classes.get(info.param_classes[chain[0]])
            if owner is not None:
                if len(chain) == 2:
                    return owner.methods.get(chain[1])
                ck2 = owner.attr_classes.get(chain[1])
                if ck2 is not None:
                    owner2 = self.classes.get(ck2)
                    if owner2 is not None:
                        return owner2.methods.get(chain[2])
            return None
        if len(chain) == 1:
            if chain[0] in mi.functions:
                return mi.functions[chain[0]]
            ck = self._lookup_class(chain[0], mi)
            if ck is not None:
                owner = self.classes.get(ck)
                if owner is not None:
                    return owner.methods.get("__init__")
            tgt = mi.imports.get(chain[0])
            if tgt and "." in tgt:
                mod, _, fname = tgt.rpartition(".")
                tmi = self.modules.get(mod)
                if tmi and fname in tmi.functions:
                    return tmi.functions[fname]
            return None
        if len(chain) == 2:
            base = mi.imports.get(chain[0])
            if base:
                tmi = self.modules.get(base)
                if tmi:
                    if chain[1] in tmi.functions:
                        return tmi.functions[chain[1]]
                    if chain[1] in tmi.classes:
                        return tmi.classes[chain[1]].methods.get("__init__")
        return None

    def resolve_jit_target(self, site: JitSite, info: FuncInfo) -> Optional[FuncInfo]:
        """The function a jit site traces, when statically resolvable."""
        if site.decorator_of is not None:
            return self.functions.get(site.decorator_of)
        chain = site.target_chain
        if chain is None:
            return None
        if (
            info.self_name
            and chain[0] == info.self_name
            and info.cls is not None
            and len(chain) == 2
        ):
            return info.cls.methods.get(chain[1])
        return self.resolve_call(info, chain)

    # -- transitive queries ------------------------------------------------

    def trans_lock_acqs(self, info: FuncInfo, _stack: Optional[set] = None):
        """All ``(lock key, bounded, holder FuncInfo key, line)`` reachable
        from ``info`` through resolvable calls (memoized, cycle-safe).

        A traversal truncated by a call cycle (some callee was already on
        the recursion stack, so its contribution is accumulated by the
        ancestor, not here) is CORRECT for the top-level caller but
        incomplete as a standalone answer — memoizing it would hand later
        queries an order-dependent subset and silently drop RL010/RL011
        edges. Only complete subtrees are cached; truncated ones recompute
        on the next top-level query."""
        memo = self._locks_memo
        if info.key in memo:
            return memo[info.key]
        stack = _stack if _stack is not None else set()
        if info.key in stack:
            return frozenset()
        stack.add(info.key)
        out: set = set()
        complete = True
        for acq in info.acquisitions:
            key = self.lock_key(acq.chain, info)
            if key is not None:
                out.add((key, acq.bounded, info.key, acq.node.lineno))
        for call in info.calls:
            callee = self.resolve_call(info, call.chain)
            if callee is not None and callee.key != info.key:
                if callee.key in stack:
                    complete = False
                    continue
                out |= self.trans_lock_acqs(callee, stack)
                if callee.key not in memo:
                    complete = False  # child itself hit a cycle
        stack.discard(info.key)
        result = frozenset(out)
        if complete:
            memo[info.key] = result
        return result

    def trans_blocking(self, info: FuncInfo, _stack: Optional[set] = None):
        """All blocking ops reachable from ``info``: (BlockOp, owner).
        Same cycle-truncation memo discipline as ``trans_lock_acqs``."""
        memo = self._block_memo
        if info.key in memo:
            return memo[info.key]
        stack = _stack if _stack is not None else set()
        if info.key in stack:
            return []
        stack.add(info.key)
        out = [(op, info) for op in info.blocking]
        complete = True
        for call in info.calls:
            callee = self.resolve_call(info, call.chain)
            if callee is not None and callee.key != info.key:
                if callee.key in stack:
                    complete = False
                    continue
                out.extend(self.trans_blocking(callee, stack))
                if callee.key not in memo:
                    complete = False
        stack.discard(info.key)
        if complete:
            memo[info.key] = out
        return out

    def daemon_reachable(self) -> set:
        """Keys of functions reachable from a ``threading.Thread(...,
        daemon=True)`` target (the monitor/daemon-thread closure for
        RL011). Non-daemon threads are excluded: the rule's contract is
        about long-lived monitors, and the repo spawns every monitor
        with the ``daemon=True`` kwarg (a ``t.daemon = True`` attribute
        assignment would be missed — documented under-approximation)."""
        roots: list[FuncInfo] = []
        for info in self.functions.values():
            for chain, daemon in info.thread_targets:
                if not daemon:
                    continue
                callee = self.resolve_call(info, chain)
                if callee is not None:
                    roots.append(callee)
        seen: set = set()
        frontier = roots
        while frontier:
            nxt: list[FuncInfo] = []
            for f in frontier:
                if f.key in seen:
                    continue
                seen.add(f.key)
                for call in f.calls:
                    callee = self.resolve_call(f, call.chain)
                    if callee is not None and callee.key not in seen:
                        nxt.append(callee)
            frontier = nxt
        return seen

    def registries(self, name: str):
        """Declared registries: (module, names, anchor, FileContext)."""
        out = []
        for mi in self.modules.values():
            if name in mi.registries:
                vals, node = mi.registries[name]
                out.append((mi.module, vals, node, mi.ctx))
        return out

    def lock_orders(self):
        out = []
        for mi in self.modules.values():
            for vals, node in mi.lock_orders:
                out.append((mi.module, vals, node, mi.ctx))
        return out

    def lockfree_decls(self):
        """Declared RL017 exemptions: (module, entries, anchor, ctx). An
        entry is ``"Owner._attr"`` / ``"<module>.<global>"``, optionally
        qualified ``"...: atomic"`` — see concurrency.parse_lockfree."""
        out = []
        for mi in self.modules.values():
            for vals, node in mi.lockfree:
                out.append((mi.module, vals, node, mi.ctx))
        return out

    def interpret_only_decls(self):
        """Declared RL022 interpret-mode registries: (module, entries,
        anchor, ctx). An entry is ``"<kernel-wrapper name>: reason"`` —
        the named module function wraps a pallas_call whose production
        (compiled) path is currently unexercised off-TPU."""
        out = []
        for mi in self.modules.values():
            for vals, node in mi.interpret_only:
                out.append((mi.module, vals, node, mi.ctx))
        return out

    def prom_refs(self):
        out = []
        for mi in self.modules.values():
            for name, node in mi.string_prom_refs:
                out.append((name, node, mi))
        return out


def _kw_int_tuple(node: ast.Call, name: str) -> Tuple[int, ...]:
    for kw in node.keywords:
        if kw.arg == name:
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                )
    return ()


def _kw_str_tuple(node: ast.Call, name: str) -> Tuple[str, ...]:
    for kw in node.keywords:
        if kw.arg == name:
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
    return ()


def _kw_expr(node: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _fill_spec_fields(site: JitSite, node: ast.Call, positional: bool) -> None:
    """Spec/mesh kwargs onto a JitSite; ``positional`` additionally maps
    ``shard_map(f, mesh, in_specs, out_specs)`` positional operands (only
    safe at direct call sites — a ``@partial(shard_map, ...)`` decorator's
    positionals bind BEFORE the traced function)."""
    site.mesh_expr = _kw_expr(node, "mesh")
    site.in_specs = _kw_expr(node, "in_specs")
    site.out_specs = _kw_expr(node, "out_specs")
    site.in_shardings = _kw_expr(node, "in_shardings")
    site.out_shardings = _kw_expr(node, "out_shardings")
    if positional and site.wrapper == "shard_map":
        pos = list(node.args[1:4]) + [None, None, None]
        if site.mesh_expr is None:
            site.mesh_expr = pos[0]
        if site.in_specs is None:
            site.in_specs = pos[1]
        if site.out_specs is None:
            site.out_specs = pos[2]
    if site.wrapper == "pmap":
        ax = _kw_expr(node, "axis_name")
        if ax is None and positional and len(node.args) >= 2:
            ax = node.args[1]
        if isinstance(ax, ast.Constant) and isinstance(ax.value, str):
            site.axis_name = (ax.value,)


def _is_spec_call(expr: Optional[ast.AST]) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    d = dotted_parts(expr.func)
    return bool(d) and d[-1] in ("P", "PartitionSpec")


def _spec_entries(call: ast.Call) -> Tuple[object, ...]:
    """P(...) positional entries: str / tuple-of-str / None / '?' (dynamic)
    / '*' (starred splat — rank unknowable)."""
    out: list = []
    for a in call.args:
        if isinstance(a, ast.Starred):
            out.append("*")
        elif isinstance(a, ast.Constant) and (
            a.value is None or isinstance(a.value, str)
        ):
            out.append(a.value)
        elif isinstance(a, (ast.Tuple, ast.List)) and a.elts and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in a.elts
        ):
            out.append(tuple(e.value for e in a.elts))
        else:
            out.append("?")
    return tuple(out)


def _literal_array_rank(expr: ast.AST) -> Optional[int]:
    """Rank of ``np.zeros((4, 8))``-style literal array constructions."""
    if not isinstance(expr, ast.Call):
        return None
    d = dotted_parts(expr.func)
    if not d or d[-1] not in ("zeros", "ones", "empty", "full"):
        return None
    if not expr.args:
        return None
    shp = expr.args[0]
    if isinstance(shp, (ast.Tuple, ast.List)):
        return len(shp.elts)
    if isinstance(shp, ast.Constant) and isinstance(shp.value, int):
        return 1
    return None


def _placement_site(node: ast.Call, fn: str) -> PlacementSite:
    sh = node.args[1] if len(node.args) >= 2 else None
    for kw in node.keywords:
        if kw.arg in ("device", "sharding"):
            sh = kw.value
    kind = "absent" if sh is None else "other"
    spec_rank = None
    if sh is not None:
        shc = dotted_parts(sh.func) if isinstance(sh, ast.Call) else None
        if shc and shc[-1] == "NamedSharding":
            kind = "named"
            if len(sh.args) >= 2 and _is_spec_call(sh.args[1]):
                entries = _spec_entries(sh.args[1])
                if "*" not in entries:
                    spec_rank = len(entries)
        elif shc and shc[-1] == "SingleDeviceSharding":
            kind = "single"
    return PlacementSite(
        fn=fn, sharding=kind, sharding_node=sh, spec_rank=spec_rank,
        operand_rank=_literal_array_rank(node.args[0]) if node.args else None,
        bound_names=(), node=node,
    )


def _block_spec(call: ast.Call) -> BlockSpecInfo:
    """``pl.BlockSpec((1, bq, d), lambda b, i, j: ...)`` — block shape
    first, index_map second (keyword spellings accepted)."""
    kws = {kw.arg: kw.value for kw in call.keywords if kw.arg}
    shp = call.args[0] if call.args else kws.get("block_shape")
    shape = None
    if isinstance(shp, (ast.Tuple, ast.List)):
        entries: list = []
        for e in shp.elts:
            if isinstance(e, ast.Constant) and (
                e.value is None or isinstance(e.value, int)
            ):
                entries.append(e.value)
            else:
                entries.append("?")
        shape = tuple(entries)
    im = call.args[1] if len(call.args) >= 2 else kws.get("index_map")
    arity = None
    if isinstance(im, ast.Lambda) and not (im.args.vararg or im.args.kwarg):
        arity = len(im.args.args)
    return BlockSpecInfo(block_shape=shape, index_map_arity=arity, node=call)


def _pallas_site(call: ast.Call, tuple_ranks: dict, gridspecs: dict) -> PallasSite:
    """Everything RL022 reads off one ``pl.pallas_call``; ``tuple_ranks``
    resolves a ``grid=grid`` local bound to a tuple literal earlier in
    the scope, ``gridspecs`` a ``grid_spec=grid_spec`` local bound to a
    ``*GridSpec(...)`` ctor."""
    kernel = call.args[0] if call.args else None
    if isinstance(kernel, ast.Call):
        kd = dotted_parts(kernel.func)
        if kd and kd[-1] == "partial" and kernel.args:
            kernel = kernel.args[0]
    kws = {kw.arg: kw.value for kw in call.keywords if kw.arg}
    grid_rank = None
    prefetch = 0
    scalar_grid = False
    spec_srcs = [kws]
    gs = kws.get("grid_spec")
    if isinstance(gs, ast.Name):
        gs = gridspecs.get(gs.id)
    if isinstance(gs, ast.Call):
        gd = dotted_parts(gs.func)
        gkws = {kw.arg: kw.value for kw in gs.keywords if kw.arg}
        spec_srcs.append(gkws)
        if gd and "Prefetch" in gd[-1]:
            scalar_grid = True
            npf = gkws.get("num_scalar_prefetch")
            if isinstance(npf, ast.Constant) and isinstance(npf.value, int):
                prefetch = npf.value
    for src in spec_srcs:
        g = src.get("grid")
        if isinstance(g, (ast.Tuple, ast.List)):
            grid_rank = len(g.elts)
        elif isinstance(g, ast.Constant) and isinstance(g.value, int):
            grid_rank = 1
        elif isinstance(g, ast.Name) and g.id in tuple_ranks:
            grid_rank = tuple_ranks[g.id]
    blocks: list = []
    for src in spec_srcs:
        for key in ("in_specs", "out_specs"):
            v = src.get(key)
            if v is None:
                continue
            elems = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elems:
                if isinstance(e, ast.Call):
                    d = dotted_parts(e.func)
                    if d and d[-1] == "BlockSpec":
                        bs = _block_spec(e)
                        bs.role = "out" if key == "out_specs" else "in"
                        blocks.append(bs)
    interp = "absent"
    ichain = None
    iv = kws.get("interpret")
    if iv is not None:
        if isinstance(iv, ast.Constant):
            interp = "true" if iv.value else "false"
        else:
            interp = "dynamic"
            if isinstance(iv, ast.Call):
                ichain = dotted_parts(iv.func)
    dims = None
    osv = kws.get("out_shape")
    if isinstance(osv, ast.Call):
        od = dotted_parts(osv.func)
        if od and od[-1] == "ShapeDtypeStruct" and osv.args:
            shp = osv.args[0]
            if isinstance(shp, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in shp.elts
            ):
                dims = tuple(e.value for e in shp.elts)
    return PallasSite(
        kernel_chain=dotted_parts(kernel) if kernel is not None else None,
        grid_rank=grid_rank, num_scalar_prefetch=prefetch,
        scalar_grid=scalar_grid, block_specs=tuple(blocks),
        interpret=interp, interpret_chain=ichain,
        out_shape_dims=dims, node=call,
    )


def build_index(
    contexts: Sequence[FileContext], display_root: Optional[Path] = None
) -> ProjectIndex:
    return ProjectIndex(contexts, display_root=display_root)
