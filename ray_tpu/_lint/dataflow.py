"""raylint phase 1.5: per-function control-flow graphs + forward dataflow.

The PR 9 index answers *"what exists and who calls whom"*; the bug classes
left after it are *path* properties — a donated buffer read on the path
between the jitted call and its reassignment, a ``KVBlockPool.allocate``
whose matching ``free`` does not dominate an exception edge, a file/lock
acquired before a raising statement that no ``finally`` covers.  This
module supplies the machinery those rules (RL013-RL016, ``rules.py``)
share; the :class:`Acquisition`/:func:`resource_leaks` ownership engine
is also reused by RL023 (``spmd.py``) for remote-DMA start/wait pairing:

* **CFG** (:func:`build_cfg`) — statement-granular basic flow for one
  ``def``: ``if``/``for``/``while``/``try``/``with`` lowered to nodes with
  normal successors, plus EXCEPTION successors on every raise-capable
  statement (contains a call, a subscript load, or is ``raise``/
  ``assert``).  ``try`` handlers receive the pre-statement state; a
  non-catch-all handler keeps an escape edge alive (an ``except OSError``
  does not stop a ``TypeError``); ``finally`` bodies are duplicated per
  continuation (normal / exceptional / return) so a release in a
  ``finally`` is seen on every path it really covers.  ``break``/
  ``continue`` jump directly to their targets (skipping ``finally`` —
  documented approximation), and nested ``def``/``lambda`` bodies are
  opaque (they execute later, not here).
* **forward engine** (:func:`fixpoint`) — worklist iteration of a
  ``transfer(node, state) -> (out, exc_out)`` function over frozenset
  states, with **may** (union) or **must** (intersection) joins.  The
  leak checks are phrased as may-analyses (a resource *may* still be
  open at an escape = the release does not *must*-dominate it).
* **donation/static summaries** (:class:`DataflowCache`) — the jit
  registry's ``donate_argnums``/``static_argnums`` lifted one call level:
  a function that passes its own parameter at a donated (static) position
  of a directly-resolvable jit call donates (fixes as static) that
  parameter for *its* callers.  Resolvable jit callables: a same-class
  ``self._step = jax.jit(...)`` attribute, a local/module-level name
  assigned from a jit call, and a local assigned from a function whose
  ``return`` is directly a jit call (``make_step_fn`` → ``step_fn``).
  Deeper indirection (tuple-unpacked factories, parameters holding jitted
  callables) is skipped — the analyses under-approximate, they never
  guess.
* **analyses** — :func:`poison_reads` (RL013: donated operands poisoned
  until reassigned, reads reported with both sites) and
  :func:`resource_leaks` (RL015/RL016: acquire → release/transfer balance
  over every exit, exception edges included, with a witness escaping
  statement per report).

Everything here is AST-only and import-free, like the rest of raylint.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

from ray_tpu._lint.index import (
    LOCK_ATTR_RE,
    FuncInfo,
    JitSite,
    ProjectIndex,
    dotted_parts,
)

# --------------------------------------------------------------------- CFG


class Node:
    """One CFG node: a simple statement or a compound-statement header."""

    __slots__ = (
        "stmt", "kind", "succ", "esucc", "line", "succ_label",
        "fallthrough_label",
    )

    def __init__(self, stmt: Optional[ast.AST], kind: str = "stmt"):
        self.stmt = stmt
        self.kind = kind  # stmt | header | entry | exit | raise | join
        self.succ: List["Node"] = []
        self.esucc: List["Node"] = []
        self.line = getattr(stmt, "lineno", 0) if stmt is not None else 0
        # If headers label their explicit branch entries ("true"/"false");
        # an edge wired later by seq() (an empty branch's fallthrough)
        # inherits fallthrough_label.  Everything else stays unlabeled.
        self.succ_label: Optional[dict] = None
        self.fallthrough_label: Optional[str] = None

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<Node {self.kind} L{self.line}>"


class CFG:
    def __init__(self):
        self.entry = Node(None, "entry")
        self.exit = Node(None, "exit")          # return / fall-off-the-end
        self.raise_exit = Node(None, "raise")   # an exception escapes the def
        self.nodes: List[Node] = [self.entry, self.exit, self.raise_exit]

    def new(self, stmt: Optional[ast.AST], kind: str = "stmt") -> Node:
        n = Node(stmt, kind)
        self.nodes.append(n)
        return n


@dataclasses.dataclass
class _ExcFrame:
    """One enclosing ``try`` as seen by a raising statement inside it."""

    handlers: List[Node]       # handler entry nodes (state flows in pre-stmt)
    catch_all: bool            # bare / Exception / BaseException handler
    fin_exc: Optional[Node]    # exceptional copy of the finally body


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    broad = {"Exception", "BaseException"}
    if isinstance(t, ast.Name) and t.id in broad:
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in broad for e in t.elts)
    return False


def scope_stmts(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a def/module body without descending into nested defs/classes
    (their statements execute in a different scope at a different time)."""
    stack = list(getattr(node, "body", []))
    while stack:
        cur = stack.pop()
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue
        yield cur
        stack.extend(ast.iter_child_nodes(cur))


def iter_expr(expr: ast.AST) -> Iterator[ast.AST]:
    """Walk an expression without descending into lambdas/comprehension
    function bodies' nested defs (they run later, not at this statement)."""
    stack = [expr]
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield cur
        stack.extend(ast.iter_child_nodes(cur))


def header_exprs(stmt: ast.AST) -> List[ast.AST]:
    """The expressions a compound-statement HEADER evaluates (its body is
    separate CFG nodes); the whole statement for simple statements."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter, stmt.target]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        out: List[ast.AST] = []
        for item in stmt.items:
            out.append(item.context_expr)
            if item.optional_vars is not None:
                out.append(item.optional_vars)
        return out
    if isinstance(stmt, ast.Try):
        return []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return []  # a def/class statement just binds a name
    return [stmt]


def _raise_capable(stmt: ast.AST) -> bool:
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    for expr in header_exprs(stmt):
        for sub in iter_expr(expr):
            if isinstance(sub, ast.Call):
                return True
            if isinstance(sub, ast.Subscript) and isinstance(sub.ctx, ast.Load):
                return True
    return False


class _Builder:
    def __init__(self, cfg: CFG):
        self.cfg = cfg

    # ``frames`` is innermost-last; break/continue/return targets are the
    # *entry* nodes control jumps to
    def seq(
        self,
        stmts: Sequence[ast.AST],
        frames: Tuple[_ExcFrame, ...],
        brk: Optional[Node],
        cont: Optional[Node],
        ret: Node,
    ) -> Tuple[Optional[Node], List[Node]]:
        """Build a statement list; returns (entry, open_exits). ``entry`` is
        None for an empty list; ``open_exits`` fall through to whatever
        comes next."""
        entry: Optional[Node] = None
        exits: List[Node] = []
        for stmt in stmts:
            head, tails = self.one(stmt, frames, brk, cont, ret)
            if head is None:
                continue
            if entry is None:
                entry = head
            for e in exits:
                e.succ.append(head)
            exits = tails
        return entry, exits

    def _exc_targets(self, frames: Tuple[_ExcFrame, ...]) -> List[Node]:
        """Where an exception raised under ``frames`` can go: every
        enclosing handler, stopping at the first catch-all; escaping
        routes through each finally's exceptional copy on the way out
        (the copy's own exits chain outward, wired at build time)."""
        out: List[Node] = []
        for frame in reversed(frames):
            out.extend(frame.handlers)
            if frame.catch_all:
                return out
            if frame.fin_exc is not None:
                out.append(frame.fin_exc)
                return out  # fin_exc's exits continue outward already
        out.append(self.cfg.raise_exit)
        return out

    def one(
        self,
        stmt: ast.AST,
        frames: Tuple[_ExcFrame, ...],
        brk: Optional[Node],
        cont: Optional[Node],
        ret: Node,
    ) -> Tuple[Optional[Node], List[Node]]:
        cfg = self.cfg
        if isinstance(stmt, ast.If):
            head = cfg.new(stmt, "header")
            self._arm(head, frames)
            b_entry, b_exits = self.seq(stmt.body, frames, brk, cont, ret)
            o_entry, o_exits = self.seq(stmt.orelse, frames, brk, cont, ret)
            head.succ_label = {}
            exits: List[Node] = []
            if b_entry is not None:
                head.succ.append(b_entry)
                head.succ_label[id(b_entry)] = "true"
                exits.extend(b_exits)
            else:
                exits.append(head)
                if o_entry is not None:
                    head.fallthrough_label = "true"
            if o_entry is not None:
                head.succ.append(o_entry)
                head.succ_label[id(o_entry)] = "false"
                exits.extend(o_exits)
            else:
                exits.append(head)
                if b_entry is not None:
                    head.fallthrough_label = "false"
            return head, exits
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = cfg.new(stmt, "header")
            self._arm(head, frames)
            # break jumps land on a join node so the loop has ONE after-exit
            join = cfg.new(None, "join")
            b_entry, b_exits = self.seq(stmt.body, frames, join, head, ret)
            if b_entry is not None:
                head.succ.append(b_entry)
                for e in b_exits:
                    e.succ.append(head)  # back edge
            e_entry, e_exits = self.seq(stmt.orelse, frames, brk, cont, ret)
            if e_entry is not None:
                head.succ.append(e_entry)  # loop exhausted -> else
                for e in e_exits:
                    e.succ.append(join)
            else:
                head.succ.append(join)  # loop-not-taken / exhausted
            return head, [join]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head = cfg.new(stmt, "header")
            self._arm(head, frames)
            b_entry, b_exits = self.seq(stmt.body, frames, brk, cont, ret)
            if b_entry is not None:
                head.succ.append(b_entry)
                return head, b_exits
            return head, [head]
        if isinstance(stmt, ast.Try):
            return self._try(stmt, frames, brk, cont, ret)
        if isinstance(stmt, ast.Return):
            node = cfg.new(stmt)
            self._arm(node, frames)
            node.succ.append(ret)
            return node, []
        if isinstance(stmt, ast.Raise):
            node = cfg.new(stmt)
            node.esucc.extend(self._exc_targets(frames))
            return node, []
        if isinstance(stmt, ast.Break):
            node = cfg.new(stmt)
            if brk is not None:
                node.succ.append(brk)
            return node, []
        if isinstance(stmt, ast.Continue):
            node = cfg.new(stmt)
            if cont is not None:
                node.succ.append(cont)
            return node, []
        # simple statement (incl. def/class bindings, which never branch)
        node = cfg.new(stmt)
        self._arm(node, frames)
        return node, [node]

    def _try(self, stmt: ast.Try, frames, brk, cont, ret):
        cfg = self.cfg
        head = cfg.new(None, "join")  # zero-width anchor for the try itself
        # exceptional finally copy: runs when the exception escapes this
        # try; its exits continue to the OUTER exception targets
        fin_exc: Optional[Node] = None
        if stmt.finalbody:
            fe, fx = self.seq(stmt.finalbody, frames, None, None, ret)
            fin_exc = fe if fe is not None else cfg.new(None, "join")
            targets = self._exc_targets(frames)
            for e in (fx if fe is not None else [fin_exc]):
                e.succ.extend(targets)
            # return-path finally copy: Return inside routes through it
            re_, rx = self.seq(stmt.finalbody, frames, None, None, ret)
            ret_entry = re_ if re_ is not None else cfg.new(None, "join")
            for e in (rx if re_ is not None else [ret_entry]):
                e.succ.append(ret)
            inner_ret = ret_entry
        else:
            inner_ret = ret
        # handler entries are join placeholders so body exception edges can
        # point at them before the handler bodies exist (no stmt payload:
        # the `except E as e:` line itself has no effects to analyze)
        h_entries = [cfg.new(None, "join") for _ in stmt.handlers]
        frame = _ExcFrame(
            handlers=list(h_entries),
            catch_all=any(_is_catch_all(h) for h in stmt.handlers),
            fin_exc=fin_exc,
        )
        body_frames = frames + (frame,)
        b_entry, b_exits = self.seq(stmt.body, body_frames, brk, cont, inner_ret)
        if b_entry is not None:
            head.succ.append(b_entry)
        else:
            b_exits = [head]
        # else runs after a clean body; its exceptions skip the handlers
        else_frames = (
            frames + (_ExcFrame([], False, fin_exc),) if fin_exc else frames
        )
        o_entry, o_exits = self.seq(stmt.orelse, else_frames, brk, cont, inner_ret)
        if o_entry is not None:
            for e in b_exits:
                e.succ.append(o_entry)
            b_exits = o_exits
        # handler bodies: exceptions inside them also skip these handlers
        h_exits: List[Node] = []
        for h, h_entry in zip(stmt.handlers, h_entries):
            hb_entry, hb_exits = self.seq(
                h.body, else_frames, brk, cont, inner_ret
            )
            if hb_entry is not None:
                h_entry.succ.append(hb_entry)
                h_exits.extend(hb_exits)
            else:
                h_exits.append(h_entry)
        open_exits = b_exits + h_exits
        if stmt.finalbody:
            fn_entry, fn_exits = self.seq(
                stmt.finalbody, frames, None, None, ret
            )
            if fn_entry is not None:
                for e in open_exits:
                    e.succ.append(fn_entry)
                return head, fn_exits
        return head, open_exits

    def _arm(self, node: Node, frames: Tuple[_ExcFrame, ...]) -> None:
        if node.stmt is not None and _raise_capable(node.stmt):
            node.esucc.extend(self._exc_targets(frames))


def build_cfg(fn: ast.AST) -> CFG:
    """CFG for one function def (or any statement-list owner)."""
    cfg = CFG()
    builder = _Builder(cfg)
    body = getattr(fn, "body", [])
    entry, exits = builder.seq(body, (), None, None, cfg.exit)
    cfg.entry.succ.append(entry if entry is not None else cfg.exit)
    for e in exits:
        e.succ.append(cfg.exit)
    return cfg


# ----------------------------------------------------------- forward engine


def fixpoint(
    cfg: CFG,
    transfer: Callable[[Node, frozenset], Tuple[frozenset, frozenset]],
    join: str = "may",
    edge_adjust: Optional[Callable[[Node, str, frozenset], frozenset]] = None,
) -> dict:
    """Forward dataflow to fixpoint; returns {node: entry-state}.

    ``join="may"`` unions states at merge points (a fact holds if it holds
    on SOME path — leak/poison detection); ``join="must"`` intersects (a
    fact holds only when EVERY path establishes it — definite-assignment
    style proofs).  Unvisited predecessors contribute nothing in either
    mode (⊥ for may, ⊤ for must).

    ``edge_adjust(node, label, out) -> out'`` refines the state flowing
    down a LABELED branch edge of an If header ("true"/"false") — the
    narrow slice of path sensitivity the conditional-acquire idiom
    (``if not pool.cache_retain(b): break``) needs."""
    states: dict = {cfg.entry: frozenset()}
    work = [cfg.entry]
    while work:
        node = work.pop()
        state = states[node]
        out, exc = transfer(node, state)
        for succs, flowed, normal in (
            (node.succ, out, True), (node.esucc, exc, False)
        ):
            for m in succs:
                here = flowed
                if normal and edge_adjust is not None:
                    label = None
                    if node.succ_label is not None:
                        label = node.succ_label.get(
                            id(m), node.fallthrough_label
                        )
                    if label is not None:
                        here = edge_adjust(node, label, flowed)
                cur = states.get(m)
                if cur is None:
                    new = here
                elif join == "may":
                    new = cur | here
                else:
                    new = cur & here
                if new != cur:
                    states[m] = new
                    work.append(m)
    return states


# ------------------------------------------- donation / static summaries


@dataclasses.dataclass(frozen=True)
class CallResolution:
    """A call statically known to reach a jit-wrapped callable."""

    donate: Tuple[int, ...]        # caller-side positional arg indices
    static: Tuple[int, ...]        # caller-side positional arg indices
    static_names: Tuple[str, ...]  # keyword names that are static
    desc: str                      # human label of the jitted target
    site_line: int                 # where the jit wrapping happens


class DataflowCache:
    """Per-run memo shared by RL013-RL016: function summaries, resolved
    call sites, CFGs.  Built lazily via :func:`get_cache`."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self._summaries: dict = {}   # FuncInfo.key -> summary | None
        self._cfgs: dict = {}        # FuncInfo.key -> CFG
        self._callmaps: dict = {}    # FuncInfo.key -> {id(call node): chain}
        self._local_jits: dict = {}  # FuncInfo.key -> {name: JitSite}
        self._resolve_memo: dict = {}  # (key, id(call)) -> resolution | None

    # -- plumbing ----------------------------------------------------------

    def cfg(self, info: FuncInfo) -> CFG:
        got = self._cfgs.get(info.key)
        if got is None:
            got = build_cfg(info.node)
            self._cfgs[info.key] = got
        return got

    def callmap(self, info: FuncInfo) -> dict:
        got = self._callmaps.get(info.key)
        if got is None:
            got = {id(cs.node): cs.chain for cs in info.calls}
            self._callmaps[info.key] = got
        return got

    def chain_of_call(self, info: FuncInfo, call: ast.Call):
        """The (alias-normalized when the index saw it) chain of a call."""
        chain = self.callmap(info).get(id(call))
        if chain is None:
            chain = dotted_parts(call.func)
        return chain

    # -- jit-site resolution -----------------------------------------------

    def _site_of_assigned_jit(self, value: ast.AST) -> Optional[JitSite]:
        return self.index._jit_site_from_call(value)

    def _local_jit_names(self, info: FuncInfo) -> dict:
        """name -> JitSite for ``fn = jax.jit(...)`` / ``fn = factory()``
        where ``factory``'s return is directly a jit call, bound to a
        LOCAL name inside ``info`` (or at module level for the module
        scope)."""
        got = self._local_jits.get(info.key)
        if got is not None:
            return got
        out: dict = {}
        for stmt in scope_stmts(info.node):
            if not isinstance(stmt, ast.Assign) or not isinstance(
                stmt.value, ast.Call
            ):
                continue
            site = self._site_of_assigned_jit(stmt.value)
            if site is None:
                # one level deeper: a call to a function whose `return` is
                # directly a jit call (make_step_fn -> step_fn)
                callee = self.index.resolve_call(
                    info, self.chain_of_call(info, stmt.value)
                )
                if callee is not None:
                    site = self._returned_jit_site(callee)
            if site is None:
                continue
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = site
        self._local_jits[info.key] = out
        return out

    def _returned_jit_site(self, info: FuncInfo) -> Optional[JitSite]:
        for stmt in scope_stmts(info.node):
            if isinstance(stmt, ast.Return) and isinstance(stmt.value, ast.Call):
                site = self.index._jit_site_from_call(stmt.value)
                if site is not None:
                    return site
        return None

    def _self_attr_jit_site(self, info: FuncInfo, attr: str) -> Optional[JitSite]:
        if info.cls is None:
            return None
        for _in_init, kind, value in info.cls.attr_assigns.get(attr, []):
            if kind == "jit_wrapper" and isinstance(value, ast.Call):
                site = self.index._jit_site_from_call(value)
                if site is not None:
                    return site
        return None

    def _module_jit_site(self, info: FuncInfo, name: str) -> Optional[JitSite]:
        mi = self.index.modules.get(info.module)
        if mi is None or mi.scope is None:
            return None
        return self._local_jit_names(mi.scope).get(name)

    def _direct_site(
        self, info: FuncInfo, call: ast.Call, local_jits: dict
    ) -> Optional[Tuple[JitSite, str]]:
        """A call whose target IS a jit-wrapped callable (no summary)."""
        chain = self.chain_of_call(info, call)
        if not chain:
            return None
        if (
            info.self_name
            and chain[0] == info.self_name
            and len(chain) == 2
        ):
            site = self._self_attr_jit_site(info, chain[1])
            if site is not None:
                return site, f"self.{chain[1]}"
        if len(chain) == 1:
            site = local_jits.get(chain[0])
            if site is None:
                site = self._module_jit_site(info, chain[0])
            if site is not None:
                return site, chain[0]
        return None

    # -- summaries ---------------------------------------------------------

    def summary(self, info: FuncInfo) -> Optional[CallResolution]:
        """One-level interprocedural summary: which of ``info``'s OWN
        positional parameters are handed to a donated/static position of a
        jit call it makes directly.  Positions are in ``info``'s parameter
        index space (``self`` included for methods)."""
        if info.key in self._summaries:
            return self._summaries[info.key]
        self._summaries[info.key] = None  # cycle guard
        args = getattr(info.node, "args", None)
        params = [a.arg for a in args.args] if args is not None else []
        pidx = {p: i for i, p in enumerate(params)}
        donate: set = set()
        static: set = set()
        site_line = 0
        desc = ""
        local_jits = self._local_jit_names(info)
        for cs in info.calls:
            got = self._direct_site(info, cs.node, local_jits)
            if got is None:
                continue
            site, label = got
            if not site.donate_argnums and not site.static_argnums:
                continue
            contributed = False
            for j, arg in enumerate(cs.node.args):
                if not isinstance(arg, ast.Name) or arg.id not in pidx:
                    continue
                if j in site.donate_argnums:
                    donate.add(pidx[arg.id])
                    contributed = True
                if j in site.static_argnums:
                    static.add(pidx[arg.id])
                    contributed = True
            # only a call that actually contributed a fact may name the
            # jit site — otherwise a later static-only call would steal
            # the citation from the donating one and RL013's message
            # would point the maintainer at the wrong wrapping
            if contributed and not site_line:
                site_line = site.node.lineno
                desc = f"{info.qualname} -> jit({label})"
        if not donate and not static:
            self._summaries[info.key] = None
            return None
        out = CallResolution(
            donate=tuple(sorted(donate)),
            static=tuple(sorted(static)),
            static_names=(),
            desc=desc,
            site_line=site_line,
        )
        self._summaries[info.key] = out
        return out

    def resolve(self, info: FuncInfo, call: ast.Call) -> Optional[CallResolution]:
        """Caller-side view of one call that reaches a jitted callable:
        which of ITS positional argument indices are donated / static.
        Direct jit targets first (returned even with no donated/static
        args — RL014's pytree check needs the bare fact of jit-ness), then
        the one-level summaries through ``resolve_call``."""
        memo_key = (info.key, id(call))
        if memo_key in self._resolve_memo:
            return self._resolve_memo[memo_key]
        out = self._resolve_uncached(info, call)
        self._resolve_memo[memo_key] = out
        return out

    def _resolve_uncached(
        self, info: FuncInfo, call: ast.Call
    ) -> Optional[CallResolution]:
        local_jits = self._local_jit_names(info)
        got = self._direct_site(info, call, local_jits)
        if got is not None:
            site, label = got
            return CallResolution(
                donate=site.donate_argnums,
                static=site.static_argnums,
                static_names=site.static_argnames,
                desc=f"jit({label})",
                site_line=site.node.lineno,
            )
        chain = self.chain_of_call(info, call)
        if not chain:
            return None
        callee = self.index.resolve_call(info, chain)
        if callee is None or callee.key == info.key:
            return None
        summ = self.summary(callee)
        if summ is None:
            return None
        # bound-method shift: `self.runner.decode_step(a, b)` binds the
        # callee's param 0 (self), so caller arg i maps to callee param i+1
        shift = 1 if callee.self_name is not None else 0
        donate = tuple(p - shift for p in summ.donate if p - shift >= 0)
        static = tuple(p - shift for p in summ.static if p - shift >= 0)
        if not donate and not static:
            return None
        return CallResolution(
            donate=donate,
            static=static,
            static_names=(),
            desc=f"{callee.qualname} ({summ.desc})",
            site_line=summ.site_line,
        )


def get_cache(index: ProjectIndex) -> DataflowCache:
    cache = getattr(index, "_dataflow_cache", None)
    if cache is None:
        cache = DataflowCache(index)
        index._dataflow_cache = cache
    return cache


# ------------------------------------------------------- statement effects


def load_chains(stmt: ast.AST) -> List[Tuple[Tuple[str, ...], ast.AST]]:
    """Maximal dotted Load chains a statement (header) reads."""
    out: List[Tuple[Tuple[str, ...], ast.AST]] = []
    covered: set = set()
    for expr in header_exprs(stmt):
        for sub in iter_expr(expr):
            if id(sub) in covered:
                continue
            if isinstance(sub, (ast.Attribute, ast.Name)) and isinstance(
                sub.ctx, ast.Load
            ):
                chain = dotted_parts(sub)
                if chain:
                    out.append((chain, sub))
                    # don't re-report the sub-chains of this chain
                    inner = sub
                    while isinstance(inner, ast.Attribute):
                        inner = inner.value
                        covered.add(id(inner))
    return out


def store_chains(stmt: ast.AST) -> List[Tuple[str, ...]]:
    """Dotted chains a statement assigns (Name/Attribute targets; a
    Subscript store ``a.b[k] = v`` reports ``a.b`` as mutated-not-rebound
    and is excluded from kills — it does not rebind the buffer)."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [
            it.optional_vars for it in stmt.items if it.optional_vars is not None
        ]
    out: List[Tuple[str, ...]] = []
    stack = list(targets)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, (ast.Name, ast.Attribute)):
            chain = dotted_parts(t)
            if chain:
                out.append(chain)
    return out


def calls_in(stmt: ast.AST) -> List[ast.Call]:
    out = []
    for expr in header_exprs(stmt):
        for sub in iter_expr(expr):
            if isinstance(sub, ast.Call):
                out.append(sub)
    return out


def _prefix(p: Tuple[str, ...], c: Tuple[str, ...]) -> bool:
    return len(p) <= len(c) and c[: len(p)] == p


# ------------------------------------------------------------ RL013 engine


@dataclasses.dataclass(frozen=True)
class PoisonRead:
    chain: Tuple[str, ...]
    read_node: ast.AST
    donate_node: ast.Call
    desc: str
    site_line: int


def poison_reads(cache: DataflowCache, info: FuncInfo) -> List[PoisonRead]:
    """RL013: donated operands are poisoned from the donating call until a
    rebinding of the chain (or a prefix of it); any read in between — on
    any path, loops and exception edges included — is a use-after-free of
    an XLA-invalidated buffer."""
    donating: dict = {}  # id(call) -> (CallResolution, call)
    for cs in info.calls:
        res = cache.resolve(info, cs.node)
        if res is not None and res.donate:
            donating[id(cs.node)] = (res, cs.node)
    if not donating:
        return []
    cfg = cache.cfg(info)
    site_info: dict = {}  # fact site id -> (call, res)

    def effects(node: Node, state: frozenset, report=None):
        stmt = node.stmt
        if stmt is None:
            return state, state
        if report is not None:
            for chain, rnode in load_chains(stmt):
                for (p, sid) in state:
                    if _prefix(p, chain):
                        call, res = site_info[sid]
                        report.append(
                            PoisonRead(
                                chain=p,  # the donated chain, not the read
                                read_node=rnode,
                                donate_node=call,
                                desc=res.desc,
                                site_line=res.site_line,
                            )
                        )
        new = set(state)
        for call in calls_in(stmt):
            got = donating.get(id(call))
            if got is None:
                continue
            res, _ = got
            site_info[id(call)] = (call, res)
            for p in res.donate:
                if p < len(call.args):
                    chain = dotted_parts(call.args[p])
                    if chain:
                        new.add((chain, id(call)))
        for tgt in store_chains(stmt):
            new = {
                (p, s) for (p, s) in new if not _prefix(tgt, p)
            }
        return frozenset(new), state

    states = fixpoint(cfg, lambda n, s: effects(n, s), join="may")
    reports: List[PoisonRead] = []
    seen: set = set()
    for node, state in states.items():
        if not state or node.stmt is None:
            continue
        found: List[PoisonRead] = []
        effects(node, state, report=found)
        for r in found:
            key = (r.chain, getattr(r.read_node, "lineno", 0), id(r.donate_node))
            if key not in seen:
                seen.add(key)
                reports.append(r)
    return reports


# ------------------------------------------------------ RL015/RL016 engine


@dataclasses.dataclass
class Acquisition:
    """One tracked acquisition inside a function."""

    call: ast.Call
    label: str                     # human label ("pool.allocate", "open")
    release_methods: Tuple[str, ...]
    receiver: Tuple[str, ...]      # chain the release must be called on; ()
    tracked_roots: Tuple[str, ...]  # names whose hand-off counts as transfer


@dataclasses.dataclass(frozen=True)
class Leak:
    acq: "Acquisition"
    escape_node: Optional[ast.AST]  # None: open at a normal exit
    kind: str                       # "raise" | "exit"


def resource_leaks(
    cache: DataflowCache,
    info: FuncInfo,
    acquisitions: List[Acquisition],
    report_normal_exit: bool = True,
) -> List[Leak]:
    """Shared RL015/RL016 balance check: every path from an acquisition to
    an exit must pass a release (matching method on the same receiver), a
    transfer (the tracked value stored into self-rooted state, appended to
    self-rooted state, or returned), before the exit.  Exception edges are
    real exits.  Normal-exit reports (``report_normal_exit``) are limited
    to acquisitions that are never resolved ANYWHERE in the function —
    conditional-acquire bookkeeping is beyond a path-insensitive lattice,
    and a function that releases on its happy path has clearly thought
    about ownership."""
    if not acquisitions:
        return []
    by_call = {id(a.call): (i, a) for i, a in enumerate(acquisitions)}
    cfg = cache.cfg(info)
    self_name = info.self_name
    ever_resolved: set = set()

    def _reads_root(expr: Optional[ast.AST], roots: Tuple[str, ...]) -> bool:
        if expr is None:
            return False
        for sub in iter_expr(expr):
            if isinstance(sub, ast.Name) and sub.id in roots:
                return True
        return False

    def _kills(stmt: ast.AST, state: frozenset) -> frozenset:
        live = set(state)
        if not live:
            return state
        # releases: <receiver>.release_method(...)
        for call in calls_in(stmt):
            chain = dotted_parts(call.func)
            if not chain or len(chain) < 2:
                continue
            meth, recv = chain[-1], chain[:-1]
            for i in list(live):
                a = acquisitions[i]
                if meth not in a.release_methods:
                    continue
                if a.receiver:
                    matched = recv == a.receiver
                else:  # value-holder resources: f.close() on the bound name
                    matched = len(recv) == 1 and recv[0] in a.tracked_roots
                if matched:
                    live.discard(i)
                    ever_resolved.add(i)
            # handoff: the tracked value passed to ANY call — appending it
            # to self-rooted state, registering it with another component
            # (faulthandler.register(file=f)), or delegating cleanup — the
            # callee is now responsible for the resource, this function is
            # no longer the leak site
            for i in list(live):
                roots = acquisitions[i].tracked_roots
                if not roots:
                    continue
                if any(
                    _reads_root(arg, roots) for arg in call.args
                ) or any(
                    _reads_root(kw.value, roots) for kw in call.keywords
                ):
                    live.discard(i)
                    ever_resolved.add(i)
        # transfers: store into self-rooted attribute / subscript where the
        # value or the subscript key reads a tracked root
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                base = tgt
                key = None
                if isinstance(tgt, ast.Subscript):
                    base, key = tgt.value, tgt.slice
                chain = dotted_parts(base)
                if not chain or self_name is None or chain[0] != self_name:
                    continue
                for i in list(live):
                    roots = acquisitions[i].tracked_roots
                    if _reads_root(stmt.value, roots) or _reads_root(key, roots):
                        live.discard(i)
                        ever_resolved.add(i)
        if isinstance(stmt, ast.Return):
            for i in list(live):
                if _reads_root(stmt.value, acquisitions[i].tracked_roots):
                    live.discard(i)
                    ever_resolved.add(i)
        # `f = open(path)` then `with f:` — the context manager's __exit__
        # now guarantees the release on every path out of the with body
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                chain = dotted_parts(item.context_expr)
                if chain is None or len(chain) != 1:
                    continue
                for i in list(live):
                    if chain[0] in acquisitions[i].tracked_roots:
                        live.discard(i)
                        ever_resolved.add(i)
        return frozenset(live)

    def transfer(node: Node, state: frozenset):
        stmt = node.stmt
        if stmt is None:
            return state, state
        new = _kills(stmt, state)
        for call in calls_in(stmt):
            got = by_call.get(id(call))
            if got is not None:
                new = new | {got[0]}
        return new, state

    # conditional acquires: `if not pool.cache_retain(b): break` — the
    # acquisition holds only on the branch where the call returned truthy
    cond_map: dict = {}
    for stmt in scope_stmts(info.node):
        if not isinstance(stmt, ast.If):
            continue
        for i, a in enumerate(acquisitions):
            pol = _polarity_in(stmt.test, a.call)
            if pol is not None:
                cond_map.setdefault(id(stmt), []).append((i, pol))

    def edge_adjust(node: Node, label: str, out: frozenset) -> frozenset:
        conds = cond_map.get(id(node.stmt)) if node.stmt is not None else None
        if not conds:
            return out
        drop = {
            i for i, positive in conds if (label == "true") != positive
        }
        return frozenset(x for x in out if x not in drop) if drop else out

    states = fixpoint(cfg, transfer, join="may", edge_adjust=edge_adjust)

    leaks: List[Leak] = []
    reported: set = set()
    # raising escapes: a raise-capable node holding an open resource whose
    # exception continuation reaches the raise exit without killing it
    for node, state in states.items():
        if not state or not node.esucc or node.stmt is None:
            continue
        for i in state:
            if ("raise", i) in reported:
                continue
            if _is_release_stmt(node.stmt, acquisitions[i]):
                # the escaping statement IS the release (a close() that
                # itself raises) — not an actionable leak. A failed
                # HANDOFF (register(file=f) raising) is NOT exempt: the
                # resource is then neither registered nor closed.
                continue
            if _escapes(node, i, acquisitions, _kills):
                reported.add(("raise", i))
                leaks.append(
                    Leak(acq=acquisitions[i], escape_node=node.stmt, kind="raise")
                )
    if report_normal_exit:
        exit_state = states.get(cfg.exit, frozenset())
        for i in exit_state:
            if i not in ever_resolved and ("exit", i) not in reported:
                reported.add(("exit", i))
                leaks.append(Leak(acq=acquisitions[i], escape_node=None, kind="exit"))
    return leaks


def _is_release_stmt(stmt: ast.AST, acq: "Acquisition") -> bool:
    """Does this statement call the acquisition's RELEASE method (close/
    release/free on the matching receiver)?  Used to exempt the release
    call itself from escape reports."""
    for call in calls_in(stmt):
        chain = dotted_parts(call.func)
        if not chain or len(chain) < 2:
            continue
        if chain[-1] not in acq.release_methods:
            continue
        recv = chain[:-1]
        if acq.receiver:
            if recv == acq.receiver:
                return True
        elif len(recv) == 1 and recv[0] in acq.tracked_roots:
            return True
    return False


def _polarity_in(test: ast.AST, call: ast.Call) -> Optional[bool]:
    """Is ``call``'s result truthy on the TRUE branch of ``test``?  True
    for ``if acquire():``, False for ``if not acquire():`` (odd number of
    enclosing ``not``s), None when the call is not in the test."""
    stack: List[Tuple[ast.AST, int]] = [(test, 0)]
    while stack:
        node, nots = stack.pop()
        if node is call:
            return nots % 2 == 0
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            stack.append((node.operand, nots + 1))
            continue
        if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for ch in ast.iter_child_nodes(node):
            stack.append((ch, nots))
    return None


def _escapes(node: Node, fact: int, acquisitions, kills_fn) -> bool:
    """Does the exception raised at ``node`` reach the function boundary
    with ``fact`` still open?  BFS the exception continuation applying
    only kill effects (state-insensitive witness check)."""
    work = list(node.esucc)
    seen: set = set()
    while work:
        cur = work.pop()
        if id(cur) in seen:
            continue
        seen.add(id(cur))
        if cur.kind == "raise":
            return True
        if cur.stmt is not None:
            if fact not in kills_fn(cur.stmt, frozenset({fact})):
                continue  # released/transferred on this continuation
        work.extend(cur.succ)
        work.extend(cur.esucc)
    return False


# ----------------------------------------------------------- RL014 helpers


_COMPREHENSIONS = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


def loop_varying_names(loop: ast.AST) -> set:
    """Names (re)bound by the loop header or anywhere in its body —
    anything whose value can differ between iterations.  Works for
    ``for``/``while`` statements AND comprehensions (whose generator
    targets vary per element exactly the same way)."""
    out: set = set()
    stack: List[ast.AST] = []
    if isinstance(loop, (ast.For, ast.AsyncFor)):
        stack.append(loop.target)
        stack.extend(loop.body)
    elif isinstance(loop, _COMPREHENSIONS):
        for gen in loop.generators:
            stack.append(gen.target)
    else:  # While
        stack.extend(loop.body)
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(cur, ast.Name) and isinstance(cur.ctx, ast.Store):
            out.add(cur.id)
        stack.extend(ast.iter_child_nodes(cur))
    return out


def names_in(expr: ast.AST) -> set:
    return {
        n.id
        for n in iter_expr(expr)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def set_built_pytree(expr: ast.AST) -> bool:
    """A dict/list argument whose keys/elements iterate a SET — pytree
    structure then depends on set iteration order, which varies run to
    run: every variation is a fresh trace."""
    for sub in iter_expr(expr):
        src = None
        if isinstance(sub, (ast.DictComp, ast.ListComp, ast.SetComp)):
            src = sub.generators[0].iter if sub.generators else None
        elif isinstance(sub, ast.GeneratorExp):
            src = sub.generators[0].iter if sub.generators else None
        if src is None:
            continue
        for s in iter_expr(src):
            if isinstance(s, ast.Set) or isinstance(s, ast.SetComp):
                return True
            if (
                isinstance(s, ast.Call)
                and isinstance(s.func, ast.Name)
                and s.func.id in ("set", "frozenset")
            ):
                return True
    return False


# lock-ish attribute names (shared with the index / RL005)
LOCKISH_RE = LOCK_ATTR_RE
