"""``--check-imports``: compile smoke check + module-level import-cycle
detection for a package tree.

Python tolerates some module-level cycles by accident of import order; they
then break the first time someone imports the modules in the other order
(typically a worker subprocess with a different entry point). We therefore
fail on *any* module-level cycle inside the scanned package. Imports inside
functions are lazy and excluded — making an import function-local is the
standard fix.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Sequence, Set


def _module_name(root: Path, file: Path) -> str:
    rel = file.relative_to(root)
    parts = (root.name,) + rel.with_suffix("").parts
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _module_level_stmts(tree: ast.Module):
    """Module-level statements, descending into if/try bodies (conditional
    imports still run at import time) but never into defs/classes."""
    stack = list(tree.body)
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        yield cur
        for field in ("body", "orelse", "finalbody", "handlers"):
            for child in getattr(cur, field, []):
                if isinstance(child, ast.ExceptHandler):
                    stack.extend(child.body)
                else:
                    stack.append(child)


def _resolve(name: str, modules: Set[str]) -> str:
    """Longest known module prefix of a dotted name ('' if external)."""
    parts = name.split(".")
    while parts:
        cand = ".".join(parts)
        if cand in modules:
            return cand
        parts.pop()
    return ""


def build_import_graph(root: Path) -> Dict[str, Set[str]]:
    files = {
        f: _module_name(root, f)
        for f in sorted(root.rglob("*.py"))
        if "__pycache__" not in f.parts
    }
    modules = set(files.values())
    pkg = root.name
    graph: Dict[str, Set[str]] = {m: set() for m in modules}

    def add_edge(mod: str, tgt: str) -> None:
        """Edge mod -> tgt, plus edges to tgt's parent packages: importing
        pkg.b.c executes pkg.b/__init__ first, so a cycle through that
        __init__ is just as real. Parents that are a prefix of ``mod``'s own
        path are skipped — a module's own ancestor packages are necessarily
        already executing when it imports, so such edges only manufacture
        false cycles out of the standard `from pkg import sibling` pattern."""
        targets = {tgt}
        parts = tgt.split(".")
        while len(parts) > 1:
            parts.pop()
            targets.add(".".join(parts))
        for t in targets:
            if t in modules and t != mod and not (mod + ".").startswith(t + "."):
                graph[mod].add(t)
    for file, mod in files.items():
        try:
            tree = ast.parse(file.read_text(encoding="utf-8", errors="replace"))
        except SyntaxError:
            continue  # py_compile pass reports this
        is_pkg_init = file.name == "__init__.py"
        for stmt in _module_level_stmts(tree):
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    tgt = _resolve(alias.name, modules)
                    if tgt:
                        add_edge(mod, tgt)
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.level:
                    # relative: strip `level` trailing components off this
                    # module's package path
                    base_parts = mod.split(".")
                    if not is_pkg_init:
                        base_parts = base_parts[:-1]
                    base_parts = base_parts[: len(base_parts) - (stmt.level - 1)]
                    base = ".".join(base_parts)
                    src = f"{base}.{stmt.module}" if stmt.module else base
                else:
                    src = stmt.module or ""
                if not src.startswith(pkg):
                    continue
                for alias in stmt.names:
                    # `from X import y`: _resolve picks the submodule X.y when
                    # it exists, else falls back to X itself — so importing a
                    # sibling submodule through the package does not create a
                    # false edge onto the package __init__
                    tgt = _resolve(f"{src}.{alias.name}", modules)
                    if tgt:
                        add_edge(mod, tgt)
    return graph


def find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components with >1 node (or a self-edge),
    iterative Tarjan."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    cycles: List[List[str]] = []

    for start in sorted(graph):
        if start in index:
            continue
        work = [(start, iter(sorted(graph[start])))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in graph:
                    continue
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph[nxt]))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or node in graph.get(node, set()):
                    cycles.append(sorted(comp))
    return cycles


def check_imports(paths: Sequence) -> List[str]:
    """Returns a list of problems (empty means clean): compile failures
    first, then import cycles."""
    problems: List[str] = []
    for raw in paths:
        root = Path(raw).resolve()
        if root.is_file():
            root = root.parent
        if not root.is_dir():
            problems.append(f"no such directory: {raw}")
            continue
        for f in sorted(root.rglob("*.py")):
            if "__pycache__" in f.parts:
                continue
            try:
                # builtin compile(): full syntax + scope checks with no
                # execution and, unlike py_compile, no __pycache__ writes
                # into the scanned tree (which breaks on read-only checkouts)
                compile(f.read_text(encoding="utf-8", errors="replace"), str(f), "exec")
            except SyntaxError as e:
                problems.append(f"compile error: {f}:{e.lineno}: {e.msg}")
            except OSError as e:
                problems.append(f"compile error: {f}: {e}")
        graph = build_import_graph(root)
        for comp in find_cycles(graph):
            problems.append(
                "module-level import cycle: " + " -> ".join(comp + [comp[0]])
                + " (break it by moving one import inside a function)"
            )
    return problems
