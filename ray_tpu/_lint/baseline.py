"""raylint baseline: recorded pre-existing violations.

The baseline is a JSON map of fingerprint (``rule:path:symbol``) -> count.
Fingerprints carry no line numbers, so edits that merely shift code do not
churn the file; a new violation of a rule in a symbol that already has
baselined ones only fires once the count grows. The intended workflow:

    python -m ray_tpu.lint ray_tpu/ --write-baseline   # adopt current state
    # ... burn entries down over time; the gate fails on anything new
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Tuple

from ray_tpu._lint.core import Violation

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = "raylint-baseline.json"


def default_baseline_path(scan_paths) -> Path:
    """Nearest ``tools/raylint-baseline.json`` walking up from the first
    scanned target, so linting a single nested file still finds the repo
    baseline. Falls back to ``<parent of root>/tools/...`` (the write
    location for ``python -m ray_tpu.lint ray_tpu/`` from the repo root)."""
    root = Path(scan_paths[0]).resolve()
    start = root if root.is_dir() else root.parent
    for d in (start, *start.parents):
        cand = d / "tools" / DEFAULT_BASELINE_NAME
        if cand.is_file():
            return cand
    return (root.parent if root.is_dir() else start) / "tools" / DEFAULT_BASELINE_NAME


def load(path: Path) -> Dict[str, int]:
    data = json.loads(Path(path).read_text())
    entries = data.get("entries", {})
    return {str(k): int(v) for k, v in entries.items()}


def write(path: Path, violations: List[Violation]) -> int:
    counts: Dict[str, int] = {}
    for v in violations:
        counts[v.fingerprint()] = counts.get(v.fingerprint(), 0) + 1
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = {
        "version": BASELINE_VERSION,
        "comment": "raylint baseline — burn down, do not grow. See LINTING.md.",
        "entries": {k: counts[k] for k in sorted(counts)},
    }
    path.write_text(json.dumps(payload, indent=2) + "\n")
    return len(violations)


def apply(
    violations: List[Violation], baseline: Dict[str, int]
) -> Tuple[List[Violation], int, List[str]]:
    """Filter baselined violations.

    Returns ``(remaining, n_baselined, stale_fingerprints)``. An entry is
    stale when any of its budget went unused — fully fixed or partially
    burned down. Stale entries must be regenerated away (the self-host gate
    enforces it): a count that stays at 3 after 2 of 3 violations were
    fixed would silently allow the 2 to regrow, defeating the ratchet."""
    budget = dict(baseline)
    remaining: List[Violation] = []
    n_baselined = 0
    for v in violations:
        fp = v.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            n_baselined += 1
        else:
            remaining.append(v)
    stale = [fp for fp, left in sorted(budget.items()) if left > 0]
    return remaining, n_baselined, stale
