"""raylint rules RL001-RL008.

Every rule is a documented heuristic, not a proof: the goal is catching the
recurring distributed-correctness mistakes of a Ray-class runtime at review
time. Anything a rule gets wrong can be silenced inline with
``# raylint: disable=RLxxx`` or recorded in the baseline — see LINTING.md.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from ray_tpu._lint.core import (
    FileContext,
    ProjectRule,
    Rule,
    Violation,
    dotted_name,
    is_actor_class,
    is_remote_def,
    register,
)
from ray_tpu._lint.index import dotted_parts


def _fallback_unserializable() -> dict:
    # kept in sync with ray_tpu.util.check_serialize; used only if that
    # module cannot be imported (e.g. linting a checkout with a broken
    # runtime package)
    return {
        "threading.Lock": "holds OS lock state",
        "threading.RLock": "holds OS lock state",
        "socket.socket": "OS socket handle",
        "open": "open file handle",
        "subprocess.Popen": "live child process",
    }


def known_unserializable_calls() -> dict:
    """dotted constructor name -> reason; shared with the runtime-side
    serializability inspector so the two stay consistent."""
    try:
        from ray_tpu.util.check_serialize import KNOWN_UNSERIALIZABLE_CALLS

        return dict(KNOWN_UNSERIALIZABLE_CALLS)
    except Exception:
        return _fallback_unserializable()


def _has_timeout(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


def _walk_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a def body without descending into nested defs (they are their
    own scopes and get visited separately when relevant)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        yield cur
        stack.extend(ast.iter_child_nodes(cur))


# --------------------------------------------------------------------- RL001


@register
class NestedBlockingGet(Rule):
    id = "RL001"
    name = "nested-blocking-get"
    description = (
        "Blocking ray_tpu.get()/ray.get() or Future.result() with no timeout "
        "inside a @remote task or actor method. If the awaited task needs a "
        "worker slot held by the caller, the cluster deadlocks (the classic "
        "nested-get deadlock). Pass timeout=, restructure to return the ref, "
        "or use ray_tpu.wait()."
    )

    _GET_NAMES = {"ray_tpu.get", "ray.get"}

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        # nested @remote defs appear both as their own scope and inside the
        # enclosing scope's walk: dedupe per call node
        reported: set = set()
        for scope in ctx.remote_scopes():
            for node in ast.walk(scope):
                if not isinstance(node, ast.Call) or id(node) in reported:
                    continue
                reported.add(id(node))
                d = dotted_name(node.func)
                if d in self._GET_NAMES and not _has_timeout(node):
                    yield ctx.violation(
                        self, node,
                        f"blocking {d}() without timeout= inside a remote "
                        "task/actor method risks a nested-get deadlock",
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "result"
                    and not node.args
                    and not node.keywords
                ):
                    yield ctx.violation(
                        self, node,
                        ".result() without timeout inside a remote task/actor "
                        "method risks a nested-get deadlock",
                    )


# --------------------------------------------------------------------- RL002


@register
class BlockingCallInAsync(Rule):
    id = "RL002"
    name = "blocking-call-in-async"
    description = (
        "Synchronous blocking call inside an async def. One blocked "
        "coroutine stalls every request multiplexed onto the actor's event "
        "loop. Use the asyncio equivalent or loop.run_in_executor()."
    )

    _BLOCKING = {
        "time.sleep": "await asyncio.sleep(...)",
        "subprocess.run": "asyncio.create_subprocess_exec(...)",
        "subprocess.call": "asyncio.create_subprocess_exec(...)",
        "subprocess.check_call": "asyncio.create_subprocess_exec(...)",
        "subprocess.check_output": "asyncio.create_subprocess_exec(...)",
        "socket.create_connection": "asyncio.open_connection(...)",
        "urllib.request.urlopen": "an async HTTP client or run_in_executor",
        "requests.get": "an async HTTP client or run_in_executor",
        "requests.post": "an async HTTP client or run_in_executor",
        "requests.request": "an async HTTP client or run_in_executor",
        "os.system": "asyncio.create_subprocess_shell(...)",
        "ray_tpu.get": "await the ref or run_in_executor",
        "ray.get": "await the ref or run_in_executor",
    }

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            # nested async defs are walked on their own; nested SYNC defs
            # are skipped — the rule's own remedy is to move the blocking
            # call into a sync helper handed to loop.run_in_executor, and
            # that fix must lint clean
            for cur in _walk_scope(node):
                if not isinstance(cur, ast.Call):
                    continue
                d = dotted_name(cur.func)
                if d in self._BLOCKING:
                    yield ctx.violation(
                        self, cur,
                        f"blocking {d}() inside async def {node.name}; "
                        f"use {self._BLOCKING[d]}",
                    )


# --------------------------------------------------------------------- RL003


@register
class UnserializableCapture(Rule):
    id = "RL003"
    name = "unserializable-closure-capture"
    description = (
        "A @remote function closes over a name bound to a known-"
        "unserializable constructor (lock, socket, file handle, ...). "
        "Submission will fail in cloudpickle with an opaque error; create "
        "the resource inside the task or move it to an actor."
    )

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        ctors = known_unserializable_calls()

        def unserializable_bindings(scope: ast.AST) -> dict:
            """name -> dotted ctor for ``name = threading.Lock()``-style
            assignments directly in ``scope`` (not in nested defs)."""
            out: dict = {}
            body = scope.body if hasattr(scope, "body") else []
            stack = list(body)
            while stack:
                cur = stack.pop()
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(cur, ast.Assign) and isinstance(cur.value, ast.Call):
                    d = dotted_name(cur.value.func)
                    if d in ctors:
                        for tgt in cur.targets:
                            if isinstance(tgt, ast.Name):
                                out[tgt.id] = d
                stack.extend(ast.iter_child_nodes(cur))
            return out

        for node in ast.walk(ctx.tree):
            if not is_remote_def(node) or isinstance(node, ast.ClassDef):
                continue
            # enclosing lexical scopes, nearest first
            enclosing = [
                a for a in ctx.ancestors(node)
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module))
            ]
            env: dict = {}
            for scope in reversed(enclosing):  # outermost first; inner shadows
                env.update(unserializable_bindings(scope))
            if not env:
                continue
            local = {a.arg for a in node.args.args + node.args.kwonlyargs}
            if node.args.vararg:
                local.add(node.args.vararg.arg)
            if node.args.kwarg:
                local.add(node.args.kwarg.arg)
            for cur in _walk_scope(node):
                if isinstance(cur, ast.Name) and isinstance(cur.ctx, ast.Store):
                    local.add(cur.id)
            for cur in _walk_scope(node):
                if (
                    isinstance(cur, ast.Name)
                    and isinstance(cur.ctx, ast.Load)
                    and cur.id not in local
                    and cur.id in env
                ):
                    yield ctx.violation(
                        self, cur,
                        f"@remote function {node.name} captures {cur.id!r} "
                        f"bound to {env[cur.id]}() "
                        f"({ctors[env[cur.id]]}); it cannot be serialized",
                    )


# --------------------------------------------------------------------- RL004


@register
class MutableDefaultOnActorMethod(Rule):
    id = "RL004"
    name = "mutable-default-arg"
    description = (
        "Mutable default argument on an actor method or @remote function. "
        "Actor methods are long-lived: the shared default accumulates state "
        "across calls and across restarts inconsistently. Use None + init."
    )

    _CTOR_NAMES = {"list", "dict", "set"}

    def _mutable_defaults(self, node) -> Iterator[ast.AST]:
        defaults = list(node.args.defaults) + [d for d in node.args.kw_defaults if d]
        for d in defaults:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                yield d
            elif (
                isinstance(d, ast.Call)
                and isinstance(d.func, ast.Name)
                and d.func.id in self._CTOR_NAMES
            ):
                yield d

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        seen = set()
        for scope in ctx.remote_scopes():
            seen.add(scope)
            for d in self._mutable_defaults(scope):
                yield ctx.violation(
                    self, d,
                    f"mutable default argument on {ctx.qualname(scope)}; "
                    "use None and initialize inside",
                )
        for node in ast.walk(ctx.tree):
            if is_remote_def(node) and node not in seen:
                for d in self._mutable_defaults(node):
                    yield ctx.violation(
                        self, d,
                        f"mutable default argument on @remote {node.name}; "
                        "use None and initialize inside",
                    )


# --------------------------------------------------------------------- RL005


@register
class InconsistentLockOrder(Rule):
    id = "RL005"
    name = "inconsistent-lock-order"
    description = (
        "Two methods of the same class acquire the same pair of locks in "
        "opposite nesting order (via with-statements). Under concurrency "
        "that is an ABBA deadlock. Pick one global order per class."
    )

    # anchored on a word start so 'clock'/'block'/'unlock' don't match
    _LOCK_ATTR_RE = re.compile(r"(?:^|_)(lock|rlock|mutex|cv|cond)s?$", re.I)

    def _lock_key(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and self._LOCK_ATTR_RE.search(expr.attr):
                return f"self.{expr.attr}"
        elif isinstance(expr, ast.Name) and self._LOCK_ATTR_RE.search(expr.id):
            return expr.id
        return None

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            # pair -> (method name, With node) of first sighting
            order: dict = {}
            reported = set()
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for pair, node in self._nested_pairs(meth):
                    order.setdefault(pair, (meth.name, node))
            for (outer, inner), (meth_name, node) in order.items():
                rev = (inner, outer)
                key = frozenset((outer, inner))
                if rev in order and key not in reported:
                    reported.add(key)
                    other = order[rev][0]
                    yield ctx.violation(
                        self, node,
                        f"{meth_name} acquires {outer} then {inner}, but "
                        f"{other} acquires {inner} then {outer} "
                        "(ABBA deadlock risk)",
                    )

    def _nested_pairs(self, meth) -> Iterator[tuple]:
        """(outer, inner) lock-name pairs from nested with-statements,
        depth-first with an explicit held-lock stack."""

        def visit(node, held):
            for cur in ast.iter_child_nodes(node):
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(cur, (ast.With, ast.AsyncWith)):
                    acquired = []
                    for item in cur.items:
                        k = self._lock_key(item.context_expr)
                        if k is not None:
                            for h in held + acquired:
                                if h != k:
                                    pairs.append(((h, k), cur))
                            acquired.append(k)
                    visit(cur, held + acquired)
                else:
                    visit(cur, held)

        pairs: list = []
        visit(meth, [])
        return iter(pairs)


# --------------------------------------------------------------------- RL006


@register
class HostSyncInHotLoop(Rule):
    id = "RL006"
    name = "host-sync-in-hot-loop"
    description = (
        "Device-to-host synchronization (.block_until_ready(), "
        "jax.device_get, np.asarray/np.array on device values) inside a "
        "loop in a hot path (ops/, train/, rl/, rlhf/). Each call stalls "
        "the XLA pipeline; hoist out of the loop or batch with "
        "jax.device_get on the whole pytree once."
    )

    HOT_DIRS = ("ops", "train", "rl", "rlhf", "llm")
    _SYNC_NAMES = {
        "jax.device_get",
        "np.asarray",
        "np.array",
        "numpy.asarray",
        "numpy.array",
        "jax.block_until_ready",
    }

    def _in_hot_path(self, ctx: FileContext) -> bool:
        parts = ctx.display_path.split("/")
        return any(d in parts for d in self.HOT_DIRS)

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not self._in_hot_path(ctx):
            return

        rule = self
        out: list = []

        class V(ast.NodeVisitor):
            def __init__(self):
                self.loop_depth = 0

            def visit_For(self, node):
                self.loop_depth += 1
                self.generic_visit(node)
                self.loop_depth -= 1

            visit_While = visit_For

            def visit_Call(self, node):
                if rule._is_sync(node) and self.loop_depth > 0:
                    out.append(
                        ctx.violation(
                            rule, node,
                            f"host sync {rule._label(node)} inside a loop in "
                            "a hot path; hoist it out or batch the transfer",
                        )
                    )
                self.generic_visit(node)

        V().visit(ctx.tree)
        yield from out

    def _is_sync(self, call: ast.Call) -> bool:
        d = dotted_name(call.func)
        if d in self._SYNC_NAMES:
            return True
        return isinstance(call.func, ast.Attribute) and call.func.attr == "block_until_ready"

    def _label(self, call: ast.Call) -> str:
        return dotted_name(call.func) or f".{call.func.attr}()"


# --------------------------------------------------------------------- RL007


@register
class SwallowedExceptionInLoop(Rule):
    id = "RL007"
    name = "swallowed-exception-in-loop"
    description = (
        "except:/except Exception: with a body of only pass/continue inside "
        "a loop. In a daemon loop this silently discards every failure "
        "forever — the classic invisible-outage bug. Log the exception "
        "(even throttled) before continuing."
    )

    _BROAD = {"Exception", "BaseException"}

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        if isinstance(t, ast.Name) and t.id in self._BROAD:
            return True
        if isinstance(t, ast.Tuple):
            return any(isinstance(e, ast.Name) and e.id in self._BROAD for e in t.elts)
        return False

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node):
                continue
            if not all(isinstance(s, (ast.Pass, ast.Continue)) for s in node.body):
                continue
            in_loop = False
            for anc in ctx.ancestors(node):
                if isinstance(anc, (ast.While, ast.For, ast.AsyncFor)):
                    in_loop = True
                    break
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    break  # the loop must be in the same scope
            if in_loop:
                yield ctx.violation(
                    self, node,
                    "broad except swallowing every error inside a loop; log "
                    "the exception (throttled) before continuing",
                )


# --------------------------------------------------------------------- RL008


@register
class ActorInitIOWithoutTimeout(Rule):
    id = "RL008"
    name = "actor-init-io-without-timeout"
    description = (
        "Actor __init__ performs network / subprocess IO with no timeout. "
        "Actor creation blocks the caller's first method call and holds a "
        "worker slot; a hung dependency turns into a hung cluster. Add a "
        "timeout or defer the IO to a ready() method."
    )

    _NEEDS_TIMEOUT = {
        "socket.create_connection",
        "urllib.request.urlopen",
        "requests.get",
        "requests.post",
        "requests.request",
        "subprocess.run",
        "subprocess.call",
        "subprocess.check_call",
        "subprocess.check_output",
    }

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for cls in ast.walk(ctx.tree):
            if not is_actor_class(cls):
                continue
            init = next(
                (
                    s for s in cls.body
                    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and s.name == "__init__"
                ),
                None,
            )
            if init is None:
                continue
            for node in _walk_scope(init):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                if d in self._NEEDS_TIMEOUT:
                    # socket.create_connection's 2nd positional is the timeout
                    if _has_timeout(node) or (
                        d == "socket.create_connection" and len(node.args) >= 2
                    ):
                        continue
                    yield ctx.violation(
                        self, node,
                        f"{d}() in actor __init__ without timeout=; a hung "
                        "peer blocks actor creation and pins a worker slot",
                    )
                elif isinstance(node.func, ast.Attribute) and node.func.attr == "connect":
                    yield ctx.violation(
                        self, node,
                        ".connect() in actor __init__; set a socket timeout "
                        "first or defer to a ready() method",
                    )


# --------------------------------------------------------------------- RL009


@register
class JitTraceCapture(ProjectRule):
    id = "RL009"
    name = "jit-trace-capture"
    description = (
        "A function handed to jax.jit/pjit/shard_map (via decorator, "
        "self._step = jax.jit(self._fn) assignment, or functools.partial) "
        "reads self.<attr> or a module-level mutable global that is model "
        "STATE (params/weights/arrays/containers, or anything reassigned "
        "after __init__), not a traced argument. The value is baked into "
        "the compiled executable at first trace — a later hot-swap "
        "(LLMEngine.update_weights) silently keeps the stale copy (the "
        "PR 7 embed/lm_head bug). Static config (ints/strs/bools/shapes, "
        "static_argnums/static_argnames) is allowed; thread state through "
        "a traced argument instead."
    )

    def check_project(self, index) -> Iterator[Violation]:
        seen: set = set()
        for site, owner in index.jit_sites:
            target = index.resolve_jit_target(site, owner)
            if target is None:
                continue
            for func, read_attr, node in self._trace_scope_reads(index, target):
                # one report per (function, attribute) — every further
                # read of the same baked attr is the same fix
                key = (func.key, read_attr or getattr(node, "id", ""))
                if key in seen:
                    continue
                seen.add(key)
                if func.cls is not None and read_attr is not None:
                    reason = self._mutable_reason(func.cls, read_attr)
                    yield func.ctx.violation(
                        self, node,
                        f"jit-traced {target.qualname} reads "
                        f"self.{read_attr} ({reason}); the value is baked "
                        "into the compiled executable at trace time — "
                        "thread it through a traced argument "
                        f"(jit site {owner.ctx.display_path}:"
                        f"{site.node.lineno})",
                    )
                elif read_attr is None:
                    # module-global mutable capture (node carries the name)
                    yield func.ctx.violation(
                        self, node,
                        f"jit-traced {target.qualname} closes over mutable "
                        f"module global {node.id!r}; the value is baked at "
                        "trace time — pass it as a traced argument "
                        f"(jit site {owner.ctx.display_path}:"
                        f"{site.node.lineno})",
                    )

    def _mutable_reason(self, cls, attr: str) -> str:
        from ray_tpu._lint.index import MUTABLE_STATE_NAMES

        assigns = cls.attr_assigns.get(attr, [])
        if any(not in_init and kind != "jit_wrapper" for in_init, kind, _ in assigns):
            return "reassigned after __init__"
        if attr in MUTABLE_STATE_NAMES or cls.attr_from_param.get(attr) in MUTABLE_STATE_NAMES:
            return "model-state name"
        return "array/container state"

    def _trace_scope_reads(self, index, target):
        """(func, attr-or-None, node) for every mutable capture reachable
        from the traced function: self.<attr> reads in same-class methods
        it calls, and mutable module-global reads in project module
        functions it calls."""
        todo = [target]
        visited = set()
        while todo:
            func = todo.pop()
            if func.key in visited:
                continue
            visited.add(func.key)
            if func.cls is not None:
                methods = func.cls.methods
                for attr, node in func.self_reads:
                    if attr in methods:
                        continue  # method access (self._qkv_rows(...))
                    kind = func.cls.attr_kind(attr)
                    if kind == "jit_wrapper":
                        continue
                    if kind == "mutable":
                        yield func, attr, node
            yield from self._global_reads(index, func)
            for call in func.calls:
                callee = index.resolve_call(func, call.chain)
                if callee is None or callee.key in visited:
                    continue
                same_class = (
                    func.cls is not None and callee.cls is func.cls
                )
                module_fn = callee.cls is None and not _is_module_scope(callee)
                if same_class or module_fn:
                    todo.append(callee)

    def _global_reads(self, index, func):
        mi = index.modules.get(func.module)
        if mi is None:
            return
        mutable = {
            n for n, kind in mi.globals.items() if kind == "mutable"
        }
        if not mutable:
            return
        local: set = set()
        args = getattr(func.node, "args", None)
        if args is not None:
            local |= {a.arg for a in args.args + args.kwonlyargs}
            if args.vararg:
                local.add(args.vararg.arg)
            if args.kwarg:
                local.add(args.kwarg.arg)
        for node in ast.walk(func.node):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                local.add(node.id)
        for node in ast.walk(func.node):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in mutable
                and node.id not in local
            ):
                yield func, None, node


def _is_module_scope(func) -> bool:
    return func.qualname == "<module>"


# --------------------------------------------------------------------- RL010


@register
class CrossModuleLockOrder(ProjectRule):
    id = "RL010"
    name = "cross-module-lock-order"
    description = (
        "The global lock-acquisition graph — every with/acquire() nesting, "
        "INCLUDING locks taken inside methods called while another lock is "
        "held, with each lock resolved to its owner (LLMEngine._lock, "
        "KVBlockPool._lock) — contains a cycle, or contradicts a declared "
        "LOCK_ORDER constant. RL005 only sees ABBA pairs inside one class; "
        "the deadlocks the runtime actually grew span engine → prefix "
        "cache → pool across modules. Bounded acquires (timeout=) cannot "
        "deadlock and add no edge. Declare the canonical order in a "
        "module-level LOCK_ORDER tuple (see ray_tpu/llm/__init__.py) and "
        "keep every acquisition path consistent with it."
    )

    def check_project(self, index) -> Iterator[Violation]:
        edges = self._build_edges(index)
        yield from self._report_cycles(edges)
        yield from self._check_declared_orders(index, edges)

    # -- graph construction ------------------------------------------------

    def _build_edges(self, index) -> dict:
        """{(outer, inner): (ctx, node, description)} — first witness per
        directed pair. Edges come from direct with-nesting and from calls
        made while holding a lock into code that (transitively) acquires
        another, both resolved to owner-qualified lock nodes."""
        edges: dict = {}

        def add(outer, inner, ctx, node, desc):
            if outer == inner:
                return
            edges.setdefault((outer, inner), (ctx, node, desc))

        for func in index.functions.values():
            held_keys_cache: dict = {}

            def resolve_held(held):
                if held not in held_keys_cache:
                    held_keys_cache[held] = [
                        k
                        for k in (index.lock_key(c, func) for c in held)
                        if k is not None
                    ]
                return held_keys_cache[held]

            for acq in func.acquisitions:
                if acq.bounded:
                    continue
                inner = index.lock_key(acq.chain, func)
                if inner is None:
                    continue
                for outer in resolve_held(acq.held):
                    add(
                        outer, inner, func.ctx, acq.node,
                        f"{func.display()}:{acq.node.lineno}",
                    )
            for call in func.calls:
                if not call.held:
                    continue
                callee = index.resolve_call(func, call.chain)
                if callee is None:
                    continue
                outers = resolve_held(call.held)
                if not outers:
                    continue
                for lock, bounded, owner_key, line in index.trans_lock_acqs(callee):
                    if bounded:
                        continue
                    owner = index.functions.get(owner_key)
                    where = owner.display() if owner else owner_key
                    for outer in outers:
                        add(
                            outer, lock, func.ctx, call.node,
                            f"{func.display()}:{call.node.lineno} -> "
                            f"{where}:{line}",
                        )
        return edges

    # -- cycle reporting ---------------------------------------------------

    def _report_cycles(self, edges: dict) -> Iterator[Violation]:
        adj: dict = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)

        def path(src, dst):
            """BFS src → dst, returns node list or None."""
            frontier = [(src, (src,))]
            seen = {src}
            while frontier:
                cur, p = frontier.pop(0)
                for nxt in adj.get(cur, ()):
                    if nxt == dst:
                        return p + (nxt,)
                    if nxt not in seen:
                        seen.add(nxt)
                        frontier.append((nxt, p + (nxt,)))
            return None

        reported: set = set()
        for (a, b), (ctx, node, desc) in sorted(edges.items()):
            back = path(b, a)
            if back is None:
                continue
            cycle_key = frozenset(back)
            if cycle_key in reported:
                continue
            reported.add(cycle_key)
            fwd_desc = desc
            back_edges = list(zip(back, back[1:]))
            back_desc = "; ".join(
                f"{x}->{y} ({edges[(x, y)][2]})" for x, y in back_edges
            )
            yield ctx.violation(
                self, node,
                f"lock-order cycle: {a} -> {b} ({fwd_desc}) but "
                f"{back_desc} — an ABBA deadlock under concurrency; pick "
                "one global order (see LOCK_ORDER)",
            )

    # -- declared-order verification ---------------------------------------

    def _check_declared_orders(self, index, edges: dict) -> Iterator[Violation]:
        observed_locks = set()
        for a, b in edges:
            observed_locks.add(a)
            observed_locks.add(b)
        for func in index.functions.values():
            for acq in func.acquisitions:
                k = index.lock_key(acq.chain, func)
                if k is not None:
                    observed_locks.add(k)
        for module, names, node, ctx in index.lock_orders():
            pos = {n: i for i, n in enumerate(names)}
            for n in names:
                if n not in observed_locks:
                    yield ctx.violation(
                        self, node,
                        f"LOCK_ORDER entry {n!r} matches no acquisition "
                        "anywhere in the project — stale or misspelled "
                        "(observed locks use Owner._attr naming)",
                    )
            for (a, b), (wctx, wnode, desc) in sorted(edges.items()):
                if a in pos and b in pos and pos[a] > pos[b]:
                    yield wctx.violation(
                        self, wnode,
                        f"acquisition {a} -> {b} ({desc}) contradicts "
                        f"LOCK_ORDER declared in {module} "
                        f"({' -> '.join(names)})",
                    )


# --------------------------------------------------------------------- RL011


@register
class BlockingUnderSharedLock(ProjectRule):
    id = "RL011"
    name = "blocking-under-lock"
    description = (
        "A blocking operation — device sync (block_until_ready, "
        "jax.device_get/put), unbounded queue.get()/.result(), network IO "
        "— runs while holding a lock that a daemon/watchdog thread ALSO "
        "acquires without a timeout. If the blocking op wedges (device "
        "hang, dead peer), the monitor thread wedges behind the same lock "
        "and can never diagnose it. This mechanizes the watchdog "
        "contract: diagnosis must not need the engine lock (RESILIENCE.md "
        "/ llm.watchdog) — monitors must use bounded acquires or lock-free "
        "beats, or the blocking op must move outside the lock. A lock "
        "whose ONLY daemon acquirer is the holding function itself (the "
        "step loop owning its own lock) does not fire."
    )

    def check_project(self, index) -> Iterator[Violation]:
        daemon = index.daemon_reachable()
        daemon_unbounded: dict = {}
        for key in daemon:
            func = index.functions.get(key)
            if func is None:
                continue
            for acq in func.acquisitions:
                if acq.bounded:
                    continue
                k = index.lock_key(acq.chain, func)
                if k is not None:
                    daemon_unbounded.setdefault(k, set()).add(func.key)
        if not daemon_unbounded:
            return
        seen: set = set()

        def fire(op, owner, lock, holder):
            others = daemon_unbounded.get(lock, set()) - {holder.key}
            if not others:
                return None
            key = (owner.key, getattr(op.node, "lineno", 0), lock)
            if key in seen:
                return None
            seen.add(key)
            other = sorted(others)[0]
            ofunc = index.functions.get(other)
            where = ofunc.display() if ofunc else other
            return owner.ctx.violation(
                self, op.node,
                f"blocking {op.label} ({op.kind}) while holding {lock}, "
                f"which the daemon-thread path {where} also acquires "
                "without a timeout — a wedge here freezes the monitor; "
                "use a bounded acquire there or move the blocking call "
                "outside the lock",
            )

        for func in index.functions.values():
            for op in func.blocking:
                for chain in op.held:
                    lock = index.lock_key(chain, func)
                    if lock is None:
                        continue
                    v = fire(op, func, lock, func)
                    if v is not None:
                        yield v
            for call in func.calls:
                if not call.held:
                    continue
                callee = index.resolve_call(func, call.chain)
                if callee is None:
                    continue
                held_locks = [
                    k
                    for k in (index.lock_key(c, func) for c in call.held)
                    if k is not None and k in daemon_unbounded
                ]
                if not held_locks:
                    continue
                for op, owner in index.trans_blocking(callee):
                    for lock in held_locks:
                        v = fire(op, owner, lock, func)
                        if v is not None:
                            yield v


# --------------------------------------------------------------------- RL012


_PROM_SUFFIXES = ("_bucket", "_count", "_sum")


@register
class ObservabilityNameDrift(ProjectRule):
    id = "RL012"
    name = "observability-name-drift"
    description = (
        "Metric/event names must stay consistent across the code that "
        "emits them (Counter/Gauge/Histogram constructors, events.record), "
        "the declared registries (module-level METRIC_NAMES/EVENT_NAMES "
        "tuples), the observability docs (OBSERVABILITY.md/RESILIENCE.md "
        "backticked names; event families like llm.* plus their suffixes), "
        "and dashboard/PromQL sources (ray_tpu_-prefixed references in "
        "string literals). Fires on: an exported name nothing documents, "
        "a registry/doc entry nothing emits, and a dashboard query over a "
        "metric nothing exports — one pass instead of scattered "
        "name-pinning tests."
    )

    def check_project(self, index) -> Iterator[Violation]:
        emitted = {"metric": {}, "event": {}}
        for site, func in index.emits:
            emitted[site.kind].setdefault(site.name, []).append((site, func))
        declared_metrics = set()
        declared_events = set()
        for _mod, names, _node, _ctx in index.registries("METRIC_NAMES"):
            declared_metrics.update(names)
        for _mod, names, _node, _ctx in index.registries("EVENT_NAMES"):
            declared_events.update(names)
        docs = index.doc_names
        prom_names = {
            self._strip(name) for name, _n, _mi in index.prom_refs()
        }

        # exported but undocumented
        for name, sites in sorted(emitted["metric"].items()):
            if name in declared_metrics or name in docs or name in prom_names:
                continue
            site, func = sites[0]
            yield func.ctx.violation(
                self, site.node,
                f"metric {name!r} is exported but appears in no "
                "METRIC_NAMES registry, observability doc, or dashboard "
                "source — document it or drop it",
            )
        for name, sites in sorted(emitted["event"].items()):
            if name in declared_events or self._event_documented(name, docs):
                continue
            site, func = sites[0]
            yield func.ctx.violation(
                self, site.node,
                f"event {name!r} is recorded but appears in no EVENT_NAMES "
                "registry or observability doc (family tables like "
                "`llm.*` + `suffix` count) — document it or drop it",
            )

        # declared but never emitted (dead registry entries)
        for _mod, names, node, ctx in index.registries("METRIC_NAMES"):
            for name in names:
                if name not in emitted["metric"]:
                    yield ctx.violation(
                        self, node,
                        f"METRIC_NAMES entry {name!r} is never exported by "
                        "any Counter/Gauge/Histogram — stale registry entry",
                    )
        for _mod, names, node, ctx in index.registries("EVENT_NAMES"):
            for name in names:
                if name not in emitted["event"]:
                    yield ctx.violation(
                        self, node,
                        f"EVENT_NAMES entry {name!r} is never recorded — "
                        "stale registry entry",
                    )

        # dashboard/PromQL references to metrics nothing exports. Skipped
        # when the scan saw no metric constructor at all (a single-file
        # lint of the dashboard module cannot judge what the rest of the
        # project exports).
        if not emitted["metric"]:
            return
        reported: set = set()
        for name, node, mi in index.prom_refs():
            stripped = self._strip(name)
            if stripped in emitted["metric"] or stripped in reported:
                continue
            reported.add(stripped)
            yield mi.ctx.violation(
                self, node,
                f"string references metric ray_tpu_{name} but nothing "
                f"exports {stripped!r} — a dashboard/alert over it would "
                "be permanently empty",
            )

    def _strip(self, name: str) -> str:
        for suf in _PROM_SUFFIXES:
            if name.endswith(suf):
                return name[: -len(suf)]
        return name

    def _event_documented(self, name: str, docs: set) -> bool:
        if name in docs:
            return True
        parts = name.split(".")
        for i in range(1, len(parts)):
            family = ".".join(parts[:i]) + ".*"
            suffix = ".".join(parts[i:])
            if family in docs and suffix in docs:
                return True
        return False


# ---------------------------------------------------------------------------
# RL013-RL016: path-sensitive dataflow rules (phase 1.5, ray_tpu._lint.dataflow)
# ---------------------------------------------------------------------------


def _analyzable_functions(index):
    """Defs worth a CFG: real functions/methods (the module pseudo-scope is
    skipped — module-level control flow is trivially linear here and the
    donating/jitted calls all live inside defs)."""
    for info in index.functions.values():
        if isinstance(info.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield info


# --------------------------------------------------------------------- RL013


@register
class UseAfterDonation(ProjectRule):
    id = "RL013"
    name = "use-after-donation"
    description = (
        "A buffer passed at a donated position (donate_argnums) of a "
        "registry-known jitted call is INVALIDATED by XLA the moment the "
        "call dispatches — the step reuses its memory for the output. "
        "Reading the same variable/attribute afterwards, on any path, "
        "before it is reassigned returns deleted-buffer errors (or, on "
        "backends that alias in place, silently garbled data). The rule "
        "runs a forward may-analysis over the per-function CFG: donated "
        "operands are poisoned at the call and cleansed only by "
        "rebinding; every read in between fires, naming both sites. "
        "Donation is resolved through the jit registry one call level "
        "deep: self._step = jax.jit(fn, donate_argnums=...) attributes, "
        "local/module names bound to jit calls (including via a factory "
        "whose return is directly a jit call), and methods that forward "
        "their parameters to a donated position (model_runner.decode_step "
        "donates its k_pool/v_pool for engine callers)."
    )

    def check_project(self, index) -> Iterator[Violation]:
        from ray_tpu._lint import dataflow

        cache = dataflow.get_cache(index)
        for info in _analyzable_functions(index):
            if not info.calls:
                continue
            for r in dataflow.poison_reads(cache, info):
                yield info.ctx.violation(
                    self, r.read_node,
                    f"use-after-donation: {'.'.join(r.chain)} was donated "
                    f"to {r.desc} at line {r.donate_node.lineno} "
                    f"(jit site line {r.site_line}) and is invalidated by "
                    "XLA; reassign it from the call's result before "
                    "reading it",
                )


# --------------------------------------------------------------------- RL014


@register
class RetraceStorm(ProjectRule):
    id = "RL014"
    name = "retrace-storm"
    description = (
        "A registry-known jitted call inside a loop whose STATIC-argument "
        "operand (static_argnums/static_argnames) varies per iteration — "
        "the loop variable or anything assigned in the loop body — "
        "recompiles on EVERY iteration: a silent 1000x slowdown that "
        "profiles as 'jax is slow'. Also fires when a pytree argument of "
        "a jitted call in a loop is built by iterating a set "
        "(set()/set-literal/set-comprehension): pytree structure then "
        "depends on unordered iteration, and every ordering is a fresh "
        "trace. Hoist the static value out of the loop, make it a traced "
        "argument, or sort the keys."
    )

    def _loop_calls(self, loop):
        """jit-candidate Call nodes inside the loop body (or, for a
        comprehension, its per-element expressions), honoring scope
        boundaries (nested defs/lambdas execute elsewhere)."""
        from ray_tpu._lint.dataflow import _COMPREHENSIONS

        if isinstance(loop, _COMPREHENSIONS):
            stack = [loop.key, loop.value] if isinstance(
                loop, ast.DictComp
            ) else [loop.elt]
            for gen in loop.generators:
                stack.extend(gen.ifs)
        else:
            stack = list(loop.body)
        while stack:
            cur = stack.pop()
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
            ):
                continue
            if isinstance(cur, ast.Call):
                yield cur
            stack.extend(ast.iter_child_nodes(cur))

    def check_project(self, index) -> Iterator[Violation]:
        from ray_tpu._lint import dataflow

        cache = dataflow.get_cache(index)
        for info in _analyzable_functions(index):
            if not info.calls:
                continue
            reported: set = set()  # (call id, kind, detail) across loops
            for loop in dataflow.scope_stmts(info.node):
                if not isinstance(
                    loop,
                    (ast.For, ast.AsyncFor, ast.While)
                    + dataflow._COMPREHENSIONS,
                ):
                    continue
                varying = dataflow.loop_varying_names(loop)
                for stmt in self._loop_calls(loop):
                    res = cache.resolve(info, stmt)
                    if res is None:
                        continue
                    for p in res.static:
                        if p >= len(stmt.args):
                            continue
                        hot = dataflow.names_in(stmt.args[p]) & varying
                        key = (id(stmt), "static", p)
                        if hot and key not in reported:
                            reported.add(key)
                            yield info.ctx.violation(
                                self, stmt,
                                f"retrace-storm: static arg {p} of "
                                f"{res.desc} (jit site line {res.site_line}) "
                                f"is built from {sorted(hot)!r}, which "
                                "varies per loop iteration — every "
                                "iteration recompiles; hoist it or make "
                                "it a traced argument",
                            )
                    for kw in stmt.keywords:
                        if kw.arg in res.static_names:
                            hot = dataflow.names_in(kw.value) & varying
                            key = (id(stmt), "static_kw", kw.arg)
                            if hot and key not in reported:
                                reported.add(key)
                                yield info.ctx.violation(
                                    self, stmt,
                                    f"retrace-storm: static kwarg "
                                    f"{kw.arg!r} of {res.desc} is built "
                                    f"from {sorted(hot)!r}, which varies "
                                    "per loop iteration — every iteration "
                                    "recompiles; hoist it or make it a "
                                    "traced argument",
                                )
                    for arg in list(stmt.args) + [k.value for k in stmt.keywords]:
                        key = (id(stmt), "pytree", 0)
                        if dataflow.set_built_pytree(arg) and key not in reported:
                            reported.add(key)
                            yield info.ctx.violation(
                                self, stmt,
                                f"retrace-storm: a pytree argument of "
                                f"{res.desc} is built by iterating a set; "
                                "pytree structure follows unordered "
                                "iteration, so orderings retrace — sort "
                                "the keys or build from an ordered source",
                            )


# --------------------------------------------------------------------- RL015


#: acquire method -> the release that balances it (KVBlockPool's ledger)
_KV_PAIRS = {"allocate": ("free",), "cache_retain": ("cache_release",)}


@register
class BlockOwnershipBalance(ProjectRule):
    id = "RL015"
    name = "block-ownership-balance"
    description = (
        "Along every path through a function that takes KV-block "
        "ownership — KVBlockPool.allocate() / cache_retain() — the "
        "matching free()/cache_release() or an ownership TRANSFER "
        "(storing the blocks/owner into self-rooted state, appending to "
        "it, or returning them) must dominate every exit, exception "
        "edges included. A path that escapes between the allocate and "
        "the transfer leaks the blocks until KVBlockPool.audit() or the "
        "watchdog notices at runtime — this rule is the static twin of "
        "that audit, catching the leak at review time. Receivers resolve "
        "through the index (an attribute annotated/constructed as "
        "KVBlockPool) or by pool-ish naming. Conditional acquires a "
        "happy path resolves are exempt from normal-exit reports (a "
        "boolean-correlated ledger is beyond a path-insensitive "
        "lattice); raising escapes always fire."
    )

    def _pool_receiver(self, index, info, recv) -> bool:
        if not recv:
            return False
        if "pool" in recv[-1].lower():
            return True
        if (
            info.cls is not None
            and info.self_name
            and recv[0] == info.self_name
            and len(recv) == 2
        ):
            ck = info.cls.attr_classes.get(recv[1])
            if ck is not None and ck[1] == "KVBlockPool":
                return True
        return False

    def _acquisitions(self, index, info):
        from ray_tpu._lint import dataflow

        out = []
        for stmt in dataflow.scope_stmts(info.node):
            if not isinstance(stmt, ast.stmt):
                continue  # scope_stmts yields every node; scan per STATEMENT
            for call in dataflow.calls_in(stmt):
                chain = dotted_parts(call.func)
                if not chain or len(chain) < 2:
                    continue
                meth, recv = chain[-1], chain[:-1]
                if meth not in _KV_PAIRS:
                    continue
                if not self._pool_receiver(index, info, recv):
                    continue
                roots = []
                if isinstance(stmt, ast.Assign):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            roots.append(tgt.id)
                if call.args:
                    key_chain = dotted_parts(call.args[0])
                    if key_chain:
                        roots.append(key_chain[0])
                out.append(
                    dataflow.Acquisition(
                        call=call,
                        label=f"{'.'.join(recv)}.{meth}",
                        release_methods=_KV_PAIRS[meth],
                        receiver=recv,
                        tracked_roots=tuple(roots),
                    )
                )
        return out

    def check_project(self, index) -> Iterator[Violation]:
        from ray_tpu._lint import dataflow

        cache = dataflow.get_cache(index)
        for info in _analyzable_functions(index):
            acqs = self._acquisitions(index, info)
            if not acqs:
                continue
            for leak in dataflow.resource_leaks(cache, info, acqs):
                a = leak.acq
                want = "/".join(a.release_methods)
                if leak.kind == "raise":
                    yield info.ctx.violation(
                        self, a.call,
                        f"block-ownership leak: {a.label}() at line "
                        f"{a.call.lineno} is not balanced by {want}() or "
                        "an ownership transfer on the exception path "
                        f"escaping from line {leak.escape_node.lineno} — "
                        "the blocks leak until the watchdog audit; "
                        "release them in an except/finally before the "
                        "error escapes",
                    )
                else:
                    yield info.ctx.violation(
                        self, a.call,
                        f"block-ownership leak: {a.label}() at line "
                        f"{a.call.lineno} reaches a return with no "
                        f"{want}() and no ownership transfer anywhere in "
                        "the function — the ledger entry outlives every "
                        "reference to it",
                    )


# --------------------------------------------------------------------- RL016


_OPEN_CTORS = {
    "open": ("close",),
    "socket.socket": ("close", "detach"),
    "socket.create_connection": ("close", "detach"),
}


@register
class UnreleasedResourceOnRaise(ProjectRule):
    id = "RL016"
    name = "unreleased-resource-on-raise"
    description = (
        "A resource acquired without a with-statement — open(), "
        "socket.socket()/create_connection(), or an unconditional "
        "lock/Condition .acquire() — where a raising path escapes the "
        "function before the matching close()/release() and no "
        "with/finally covers it. Handlers count: a release inside an "
        "except/finally that re-raises is a covered path, and a "
        "catch-all handler stops the escape; a narrow handler "
        "(except OSError) does NOT stop other exception types, so the "
        "escape edge survives it. Intentionally process-lifetime "
        "resources are fine on the NORMAL path — only raising escapes "
        "fire. Conditional acquires (blocking=False / timeout=) are "
        "skipped: their ownership is boolean-correlated (RL011 covers "
        "their deadlock half)."
    )

    def _acquisitions(self, info):
        from ray_tpu._lint import dataflow

        out = []
        with_items: set = set()
        for stmt in dataflow.scope_stmts(info.node):
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    for sub in dataflow.iter_expr(item.context_expr):
                        with_items.add(id(sub))
        for stmt in dataflow.scope_stmts(info.node):
            if not isinstance(stmt, ast.stmt):
                continue  # scope_stmts yields every node; scan per STATEMENT
            for call in dataflow.calls_in(stmt):
                if id(call) in with_items:
                    continue
                chain = dotted_parts(call.func)
                if not chain:
                    continue
                dotted = ".".join(chain)
                # `import socket as _socket` still reads as *socket.socket
                socket_alias = (
                    len(chain) == 2
                    and chain[-1] in ("socket", "create_connection")
                    and "socket" in chain[0]
                )
                if dotted in _OPEN_CTORS or socket_alias:
                    releases = _OPEN_CTORS.get(dotted, ("close", "detach"))
                    roots = []
                    # only a DIRECT binding (`f = open(...)`) is trackable;
                    # an open() buried in a comprehension/argument has no
                    # name whose close()/handoff we could observe
                    if isinstance(stmt, ast.Assign) and stmt.value is call:
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name):
                                roots.append(tgt.id)
                    if not roots:
                        continue  # unbound resource: nothing to track
                    out.append(
                        dataflow.Acquisition(
                            call=call,
                            label=f"{dotted}()",
                            release_methods=releases,
                            receiver=(),
                            tracked_roots=tuple(roots),
                        )
                    )
                elif (
                    chain[-1] == "acquire"
                    and len(chain) > 1
                    and dataflow.LOCKISH_RE.search(chain[-2])
                    and not call.args
                    and not call.keywords
                ):
                    out.append(
                        dataflow.Acquisition(
                            call=call,
                            label=f"{'.'.join(chain[:-1])}.acquire()",
                            release_methods=("release",),
                            receiver=chain[:-1],
                            tracked_roots=(),
                        )
                    )
        return out

    def check_project(self, index) -> Iterator[Violation]:
        from ray_tpu._lint import dataflow

        cache = dataflow.get_cache(index)
        for info in _analyzable_functions(index):
            acqs = self._acquisitions(info)
            if not acqs:
                continue
            leaks = dataflow.resource_leaks(
                cache, info, acqs, report_normal_exit=False
            )
            for leak in leaks:
                a = leak.acq
                yield info.ctx.violation(
                    self, a.call,
                    f"unreleased resource on raise: {a.label} acquired at "
                    f"line {a.call.lineno} escapes via the exception "
                    f"raised at line {leak.escape_node.lineno} without "
                    f"{'/'.join(a.release_methods)}() and no with/finally "
                    "covers it; release it on the exception path",
                )


# ---------------------------------------------------------------------------
# RL017-RL019: thread/ownership + wire-protocol rules (phase 1.9,
# ray_tpu._lint.concurrency)
# ---------------------------------------------------------------------------


# --------------------------------------------------------------------- RL017


@register
class CrossThreadRace(ProjectRule):
    id = "RL017"
    name = "cross-thread-race"
    description = (
        "A mutable attribute or module global is MUTATED (augmented "
        "assignment / container mutation — the access kinds that corrupt; "
        "plain rebinds are GIL-atomic publishes) from one thread root "
        "while another root writes it under a disjoint lock set — or "
        "accesses it at all when the mutation holds no lock. Thread roots "
        "come from the index's spawn sites (threading.Thread targets "
        "incl. lambdas, executor .submit()/run_in_executor hand-offs) "
        "plus the external-caller surface; guards come from RacerD-style "
        "guarded-by inference over per-site held-lock sets, including "
        "linear .acquire()/.release() bracketing and locks inherited "
        "through the call graph. __init__ is pre-publication; attributes "
        "holding Queue/Event/Lock-style primitives are internally "
        "synchronized; both are exempt. Deliberate lock-free designs are "
        "DECLARED in a module-level LOCKFREE tuple (like LOCK_ORDER) and "
        "VERIFIED: a bare 'Owner._attr' entry asserts single-writer (≥2 "
        "writing roots is an error), 'Owner._attr: atomic' asserts every "
        "write is one GIL-atomic operation (a read-modify-write += fails "
        "verification), and an entry matching no accessed state is "
        "stale. Anything else gets a lock, or an inline suppression with "
        "a written justification."
    )

    def check_project(self, index) -> Iterator[Violation]:
        from ray_tpu._lint import concurrency

        model = concurrency.get_model(index)
        declared: dict = {}
        for module, entries, node, ctx in index.lockfree_decls():
            for entry in entries:
                key, qual = concurrency.parse_lockfree(entry)
                if "." not in key:
                    # a bare name declares a global of the DECLARING module
                    key = f"{module}.{key}"
                declared[key] = (qual, node, ctx, module, entry)

        for state, accs, (s1, s2), roots in model.races():
            key = concurrency.state_display(state)
            if key in declared:
                continue  # verified separately below
            # anchor at the LESS-guarded write when there is one (that is
            # where the fix or the justified suppression belongs — e.g. a
            # test-hook reset racing a locked hot path)
            if s2.kind != "read" and len(s2.locks) <= len(s1.locks):
                s1, s2 = s2, s1
            l1 = ",".join(sorted(s1.locks)) or "no lock"
            l2 = ",".join(sorted(s2.locks)) or "no lock"
            verb1 = "read" if s1.kind == "read" else (
                "mutated" if s1.kind in ("aug", "mutate") else "written"
            )
            verb2 = "reads" if s2.kind == "read" else (
                "mutates" if s2.kind in ("aug", "mutate") else "writes"
            )
            yield s1.func.ctx.violation(
                self, s1.node,
                f"cross-thread race on {key}: {verb1} at "
                f"{s1.func.ctx.display_path}:{s1.node.lineno} "
                f"[{s1.root}, {l1}] while "
                f"{s2.func.ctx.display_path}:{s2.node.lineno} "
                f"[{s2.root}, {l2}] {verb2} it with no "
                f"common lock (state touched from {len(roots)} roots: "
                f"{', '.join(sorted(roots))}); guard it with one lock, or "
                "declare the lock-free design in LOCKFREE with a "
                "justification",
            )

        # verify the declarations themselves
        seen_keys = set(model.by_display)
        for key, (qual, node, ctx, module, entry) in sorted(declared.items()):
            if qual not in (None, "atomic"):
                yield ctx.violation(
                    self, node,
                    f"LOCKFREE entry {entry!r} has unknown qualifier "
                    f"{qual!r} (use a bare 'Owner._attr' for single-writer "
                    "or 'Owner._attr: atomic')",
                )
                continue
            if key not in seen_keys:
                yield ctx.violation(
                    self, node,
                    f"LOCKFREE entry {key!r} matches no accessed "
                    "attribute/global anywhere in the project — stale or "
                    "misspelled (entries use Owner._attr / module.global "
                    "naming, like lock keys)",
                )
                continue
            accs = [
                a
                for st in model.by_display[key]
                for a in model.accesses[st]
            ]
            wr = [a for a in accs if a.kind in ("store", "aug", "mutate")]
            if qual is None:
                wroots = {a.root for a in wr}
                if len(wroots) >= 2:
                    w0 = self._pick(wr, prefer_not=concurrency.CALLER)
                    yield ctx.violation(
                        self, node,
                        f"LOCKFREE entry {key!r} declares single-writer "
                        f"but it is written from {len(wroots)} thread "
                        f"roots ({', '.join(sorted(wroots))} — e.g. "
                        f"{w0.func.ctx.display_path}:{w0.node.lineno}); "
                        "the declaration no longer holds: add a lock or "
                        "re-justify as ': atomic'",
                    )
            else:  # atomic
                bad = [a for a in wr if a.kind == "aug"]
                if bad:
                    yield ctx.violation(
                        self, node,
                        f"LOCKFREE entry {key!r} declares atomic "
                        "single-operation writes but "
                        f"{bad[0].func.ctx.display_path}:"
                        f"{bad[0].node.lineno} is a read-modify-write "
                        "augmented assignment — not atomic under "
                        "preemption; use a lock or a single-writer design",
                    )

    def _pick(self, accs, prefer_not: str):
        for a in accs:
            if a.root != prefer_not:
                return a
        return accs[0]


# --------------------------------------------------------------------- RL018


@register
class AtomicityViolation(ProjectRule):
    id = "RL018"
    name = "check-then-act"
    description = (
        "An attribute is READ under `with <lock>` in one block and "
        "WRITTEN under a SEPARATE `with <lock>` later in the same "
        "function, with the write gated by a test on the checked value — "
        "the lock was RELEASED between the check and the act, so the "
        "condition can be stale by the time the act runs (the PR 14 "
        "credit-window / _sent_hdrs review-round bug shape: a double "
        "decrement driven by a check another thread already consumed). "
        "Narrow by design: only fires when the gate demonstrably reads a "
        "local bound inside the check block or the attribute itself. Fix "
        "by re-checking under the second acquisition (and acting on the "
        "re-checked value), or by widening one critical section over "
        "check and act."
    )

    def check_project(self, index) -> Iterator[Violation]:
        from ray_tpu._lint import concurrency

        for info in _analyzable_functions(index):
            for hit in concurrency.check_then_act(index, info):
                yield info.ctx.violation(
                    self, hit.act_node,
                    f"check-then-act on {hit.attr!r}: checked under "
                    f"{hit.lock} at line {hit.check_node.lineno}, lock "
                    "released, then acted on at line "
                    f"{hit.act_node.lineno} under a fresh acquisition "
                    f"(gate at line {hit.gate_node.lineno}) — the checked "
                    "condition can be stale; re-check under the second "
                    "acquisition or widen the critical section",
                )


# --------------------------------------------------------------------- RL019


#: send-side buffered-structure attribute names the reconnect axe audits
_WIRE_BUFFER_RE = re.compile(r"(^|_)(buf|buffer|outbox|unacked)(s)?$")

#: functions that count as a sweep/recovery path for buffered wire state
_SWEEP_FN_RE = re.compile(r"(fail|reconnect|flush|drain|sweep|retry|requeue)", re.I)


@register
class ProtocolMessageDrift(ProjectRule):
    id = "RL019"
    name = "protocol-message-drift"
    description = (
        "The wire protocol's send sites and dispatch tables must agree. "
        "The index records every message kind PRODUCED (a ('kind', ...) "
        "tuple literal reaching send/send_raw/conn_send/_send, directly "
        "or through one local/ternary hop) and every kind HANDLED (a "
        "kind == 'lit' comparison on a recv-rooted value: a local from "
        "conn.recv()/reader.read_available(), its [0] projection, or a "
        "parameter a caller fills with one — promoted one call level). "
        "Fires on: a kind sent that no dispatch handles (the message is "
        "silently dropped by every recv loop), and a handler for a kind "
        "nothing sends (dead protocol — RL012's name-drift discipline "
        "applied to the wire). The reconnect axe: a send-side buffered "
        "structure (submit outbox, reply batch, un-acked window map — "
        "*_buf/*_outbox/*_unacked attributes in modules that send) with "
        "no sweep reachable from any fail/reconnect/flush/drain-named "
        "function leaks its contents forever when the connection dies. "
        "Single-file scans are guarded: with no send (or no handler) "
        "sites in view, the opposite direction is not judged."
    )

    def check_project(self, index) -> Iterator[Violation]:
        sends: dict = {}
        handled: dict = {}
        param_compares: dict = {}  # (func key, param) -> [(kind, node, func)]
        param_senders: dict = {}   # func key -> set of kind-carrying params
        for info in index.functions.values():
            for kind, node in info.msg_sends:
                sends.setdefault(kind, []).append((node, info))
            for pname, _node in info.msg_param_sends:
                param_senders.setdefault(info.key, set()).add(pname)
            for mc in info.msg_compares:
                if mc.root == "recv":
                    handled.setdefault(mc.kind, []).append((mc.node, info))
                elif isinstance(mc.root, tuple) and mc.root[0] == "msg":
                    param_compares.setdefault(
                        (info.key, mc.root[1]), []
                    ).append((mc.kind, mc.node, info))
        # one-level promotion, both directions: a parameter a caller
        # fills with a recv-rooted message counts as recv-rooted in the
        # callee (handler side); a string literal a caller passes at a
        # kind-carrying parameter position counts as a send of that kind
        # (send side — the _broadcast_rendezvous("profile", ...) shape)
        if param_compares or param_senders:
            for info in index.functions.values():
                for cs in info.calls:
                    callee = index.resolve_call(info, cs.chain)
                    if callee is None:
                        continue
                    args = getattr(callee.node, "args", None)
                    if args is None:
                        continue
                    params = [a.arg for a in args.args]
                    shift = 1 if callee.self_name is not None else 0
                    sender_params = param_senders.get(callee.key)
                    for i, arg in enumerate(cs.node.args):
                        pi = i + shift
                        if pi >= len(params):
                            continue
                        if (
                            isinstance(arg, ast.Name)
                            and arg.id in info.recv_names
                        ):
                            got = param_compares.get((callee.key, params[pi]))
                            if got:
                                for kind, node, owner in got:
                                    handled.setdefault(kind, []).append(
                                        (node, owner)
                                    )
                        elif (
                            sender_params
                            and params[pi] in sender_params
                            and isinstance(arg, ast.Constant)
                            and isinstance(arg.value, str)
                        ):
                            sends.setdefault(arg.value, []).append(
                                (cs.node, info)
                            )
        if sends and handled:
            for kind in sorted(sends):
                if kind in handled:
                    continue
                node, info = sends[kind][0]
                yield info.ctx.violation(
                    self, node,
                    f"message kind {kind!r} is sent here but no recv-loop "
                    "dispatch anywhere in the project handles it — every "
                    "receiver silently drops it (or the handler's compare "
                    "is not recv-rooted and the index cannot see it)",
                )
            for kind in sorted(handled):
                if kind in sends:
                    continue
                node, info = handled[kind][0]
                yield info.ctx.violation(
                    self, node,
                    f"dispatch handles message kind {kind!r} but nothing "
                    "in the project sends it — dead protocol (or the send "
                    "site builds the tuple too dynamically for the index; "
                    "route it through a kind-headed literal)",
                )
        yield from self._reconnect_sweeps(index, sends)

    def _reconnect_sweeps(self, index, sends: dict) -> Iterator[Violation]:
        if not sends:
            return
        send_modules = {info.module for sites in sends.values() for _n, info in sites}
        # attribute names referenced anywhere inside sweep-named functions
        # (their nested defs fold in) and their directly-resolvable callees
        swept: set = set()
        sweep_funcs = [
            f for f in index.functions.values() if _SWEEP_FN_RE.search(f.name)
        ]
        seen: set = set()
        frontier = list(sweep_funcs)
        depth = 0
        while frontier and depth < 3:
            nxt = []
            for f in frontier:
                if f.key in seen:
                    continue
                seen.add(f.key)
                for a in f.attr_accesses:
                    swept.add(a.chain[-1])
                for call in f.calls:
                    callee = index.resolve_call(f, call.chain)
                    if callee is not None and callee.key not in seen:
                        nxt.append(callee)
            frontier = nxt
            depth += 1
        for (module, cname), ci in sorted(index.classes.items()):
            if module not in send_modules:
                continue
            for attr, assigns in sorted(ci.attr_assigns.items()):
                if not _WIRE_BUFFER_RE.search(attr):
                    continue
                if attr in swept:
                    continue
                anchor = next(
                    (v for _init, _k, v in assigns if v is not None), None
                )
                node = anchor if anchor is not None else ci.node
                yield ci.ctx.violation(
                    self, node,
                    f"buffered wire structure {cname}.{attr} has no sweep "
                    "reachable from any fail/reconnect/flush/drain path — "
                    "a connection loss strands whatever it buffered "
                    "(refs never resolve, completions never re-ship); "
                    "fail or re-ship its contents from the reconnect "
                    "sweep (_fail_submits/_try_reconnect shape)",
                )


# ---------------------------------------------------------------------------
# RL020-RL024: mesh / sharding / Pallas contract rules (phase 2.1,
# ray_tpu._lint.spmd)
# ---------------------------------------------------------------------------


# --------------------------------------------------------------------- RL020


@register
class UnboundCollectiveAxis(ProjectRule):
    id = "RL020"
    name = "unbound-collective-axis"
    description = (
        "A collective (psum/pmean/ppermute/all_gather/psum_scatter/"
        "all_to_all/axis_index/axis_size) names a LITERAL axis that no "
        "enclosing shard_map/pmap can bind: the call raises NameError-"
        "style trace errors ('unbound axis name') the first time the "
        "function is actually traced under a mesh — typically in the "
        "multi-chip path that unit tests never reach. Binding "
        "environments come from the jit registry: every shard_map/pmap "
        "site contributes its resolved mesh axes to the traced target "
        "AND the site's owner scope (nested-def bodies fold into the "
        "owner); a function's allowed set is its own env unioned with "
        "its direct callers' envs, one level deep. A site whose mesh is "
        "opaque (parameter meshes) contributes ANY, which suppresses "
        "the rule — it can miss, it must not invent. Collectives whose "
        "axis is a parameter are promoted to callers passing a literal "
        "axis (or relying on a literal default) when neither side can "
        "bind it."
    )

    def check_project(self, index) -> Iterator[Violation]:
        from ray_tpu._lint import spmd

        model = spmd.get_model(index)
        for hit in model.collective_violations():
            where = (
                f" (reached through {hit.via} from this call)"
                if hit.via
                else ""
            )
            yield hit.info.ctx.violation(
                self, hit.node,
                f"collective {hit.op} names axis {hit.axis!r} but no "
                f"enclosing shard_map/pmap binds it{where} — tracing "
                "under a mesh raises 'unbound axis name'; wrap the call "
                "in a shard_map over a mesh with that axis or thread the "
                "axis name from the binding site",
            )


# --------------------------------------------------------------------- RL021


@register
class SpecMeshDrift(ProjectRule):
    id = "RL021"
    name = "spec-mesh-drift"
    description = (
        "A PartitionSpec disagrees with the mesh or operand it runs "
        "against: a P(...) literal reachable from a shard_map site's "
        "in_specs/out_specs (or paired inside NamedSharding(mesh, "
        "P(...))) names an axis the resolved mesh does not have — a "
        "KeyError at trace time, or silent replication when the axis "
        "exists on a different mesh; an in_specs tuple whose arity "
        "cannot match the traced target's visible parameter span "
        "(functools.partial pre-bound positions/keywords shrink it, "
        "defaults widen the lower bound) — a pytree structure error on "
        "first call; or a placement whose P names more dims than its "
        "literal-rank operand has. Parameter meshes and dynamic spec "
        "entries are skipped (documented under-approximations)."
    )

    def check_project(self, index) -> Iterator[Violation]:
        from ray_tpu._lint import spmd

        model = spmd.get_model(index)
        for hit in model.spec_violations():
            yield hit.info.ctx.violation(self, hit.node, hit.detail)


# --------------------------------------------------------------------- RL022


@register
class PallasContractDrift(ProjectRule):
    id = "RL022"
    name = "pallas-contract-drift"
    description = (
        "A pl.pallas_call whose static contract is internally "
        "inconsistent, or whose compiled path has silently lost "
        "coverage. Shape checks: a BlockSpec index_map whose arity "
        "differs from the grid rank (plus num_scalar_prefetch under a "
        "PrefetchScalarGridSpec — scalar-prefetch operands are "
        "prepended to every index_map) fails inside Mosaic with an "
        "arity error naming neither site; an out-block dim that "
        "provably does not divide a literal out_shape dim, with no "
        "masking evidence (pl.when / mask identifiers) in the resolved "
        "kernel, reads/writes out of bounds in the tail block. "
        "Coverage: an interpret-GATED kernel wrapper (interpret=True "
        "hardcoded, or a same-module dispatcher that calls it and "
        "branches on its gate call as an un-negated disjunct — 'if "
        "_interpret() or ...: return xla_path' routes AWAY from the "
        "compiled path exactly where CI runs) must be declared in a "
        "module-level INTERPRET_ONLY registry with a reason, so the "
        "ROADMAP's 'kernels still gated to interpret mode' debt is "
        "machine-tracked. The registry is verified bidirectionally: "
        "undeclared gated wrappers fire, and stale entries naming no "
        "gated wrapper fire, so un-gating a kernel forces the entry to "
        "be retired with the debt."
    )

    def check_project(self, index) -> Iterator[Violation]:
        from ray_tpu._lint import spmd

        model = spmd.get_model(index)
        for hit in model.pallas_violations():
            yield hit.ctx.violation(self, hit.node, hit.detail)


# --------------------------------------------------------------------- RL023


@register
class UnpairedRemoteDma(ProjectRule):
    id = "RL023"
    name = "unpaired-remote-dma"
    description = (
        "A make_async_remote_copy handle whose .start() has a path to "
        "function exit — exception edges included — that skips the "
        "matching .wait(): the send/recv semaphore stays permanently "
        "unsignaled on the peer chip, and the NEXT DMA on that "
        "semaphore deadlocks the whole mesh, arbitrarily far from the "
        "cause (the failure mode the Pallas async-copy docs warn "
        "about). RL015's ownership machinery applied to DMA handles: "
        ".wait()/.wait_send()/.wait_recv() release; handing the handle "
        "to a call, returning it, or entering it as a context manager "
        "transfers ownership to the receiver."
    )

    def check_project(self, index) -> Iterator[Violation]:
        from ray_tpu._lint import dataflow, spmd

        cache = dataflow.get_cache(index)
        model = spmd.get_model(index)
        for info in _analyzable_functions(index):
            if not info.dma_binds:
                continue
            acqs = model.dma_acquisitions(info)
            if not acqs:
                continue
            for leak in dataflow.resource_leaks(
                cache, info, acqs, report_normal_exit=True
            ):
                if leak.kind == "raise":
                    yield info.ctx.violation(
                        self, leak.escape_node,
                        f"remote DMA {leak.acq.label} (line "
                        f"{leak.acq.call.lineno}) can escape here without "
                        "its .wait() — the semaphore stays unsignaled on "
                        "the peer and the next DMA on it deadlocks the "
                        "mesh; wait (or wait_send/wait_recv) on every "
                        "path, including exception edges",
                    )
                else:
                    yield info.ctx.violation(
                        self, leak.acq.call,
                        f"remote DMA {leak.acq.label} is started but no "
                        "path waits on it before exit — the transfer is "
                        "never synchronized and the semaphore leaks; pair "
                        "every start() with wait()",
                    )


# --------------------------------------------------------------------- RL024


@register
class ShardingDrift(ProjectRule):
    id = "RL024"
    name = "sharding-drift"
    description = (
        "A value placed on the DEFAULT device (device_put with no "
        "sharding operand) or with an explicit SingleDeviceSharding "
        "flows into a registry-resolved jitted call whose matching "
        "positional in_shardings entry is a NamedSharding: every call "
        "re-lays-out the operand across the mesh and, when the "
        "committed sharding differs, retraces — the exact bug PR 13 "
        "fixed in shard_train_state (step counter placed single-device "
        "against a mesh-jitted step fn, silently recompiling fwd+bwd "
        "every train step; 2x step time, no exception). Flagged at the "
        "PLACEMENT site, where the fix goes. Requires the placed value "
        "bound to a name and passed as that bare name in the same "
        "function (placement before call in source order); a later re-"
        "placement with a NamedSharding clears it."
    )

    def check_project(self, index) -> Iterator[Violation]:
        from ray_tpu._lint import dataflow, spmd

        cache = dataflow.get_cache(index)
        model = spmd.get_model(index)
        for hit in model.drift_violations(cache):
            name = hit.placement.bound_names[0]
            how = (
                "an explicit SingleDeviceSharding"
                if hit.placement.sharding == "single"
                else "no sharding operand (committed to the default device)"
            )
            yield hit.info.ctx.violation(
                self, hit.placement.node,
                f"{name} is placed with {how} but flows into {hit.jit_label} "
                f"(line {hit.call_node.lineno}) whose in_shardings[{hit.pos}] "
                "is a NamedSharding — every call re-lays-out the operand "
                "and retraces on sharding mismatch; place it with the "
                "matching NamedSharding up front",
            )
