"""raylint rules RL001-RL008.

Every rule is a documented heuristic, not a proof: the goal is catching the
recurring distributed-correctness mistakes of a Ray-class runtime at review
time. Anything a rule gets wrong can be silenced inline with
``# raylint: disable=RLxxx`` or recorded in the baseline — see LINTING.md.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from ray_tpu._lint.core import (
    FileContext,
    Rule,
    Violation,
    dotted_name,
    is_actor_class,
    is_remote_def,
    register,
)


def _fallback_unserializable() -> dict:
    # kept in sync with ray_tpu.util.check_serialize; used only if that
    # module cannot be imported (e.g. linting a checkout with a broken
    # runtime package)
    return {
        "threading.Lock": "holds OS lock state",
        "threading.RLock": "holds OS lock state",
        "socket.socket": "OS socket handle",
        "open": "open file handle",
        "subprocess.Popen": "live child process",
    }


def known_unserializable_calls() -> dict:
    """dotted constructor name -> reason; shared with the runtime-side
    serializability inspector so the two stay consistent."""
    try:
        from ray_tpu.util.check_serialize import KNOWN_UNSERIALIZABLE_CALLS

        return dict(KNOWN_UNSERIALIZABLE_CALLS)
    except Exception:
        return _fallback_unserializable()


def _has_timeout(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


def _walk_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a def body without descending into nested defs (they are their
    own scopes and get visited separately when relevant)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        yield cur
        stack.extend(ast.iter_child_nodes(cur))


# --------------------------------------------------------------------- RL001


@register
class NestedBlockingGet(Rule):
    id = "RL001"
    name = "nested-blocking-get"
    description = (
        "Blocking ray_tpu.get()/ray.get() or Future.result() with no timeout "
        "inside a @remote task or actor method. If the awaited task needs a "
        "worker slot held by the caller, the cluster deadlocks (the classic "
        "nested-get deadlock). Pass timeout=, restructure to return the ref, "
        "or use ray_tpu.wait()."
    )

    _GET_NAMES = {"ray_tpu.get", "ray.get"}

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        # nested @remote defs appear both as their own scope and inside the
        # enclosing scope's walk: dedupe per call node
        reported: set = set()
        for scope in ctx.remote_scopes():
            for node in ast.walk(scope):
                if not isinstance(node, ast.Call) or id(node) in reported:
                    continue
                reported.add(id(node))
                d = dotted_name(node.func)
                if d in self._GET_NAMES and not _has_timeout(node):
                    yield ctx.violation(
                        self, node,
                        f"blocking {d}() without timeout= inside a remote "
                        "task/actor method risks a nested-get deadlock",
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "result"
                    and not node.args
                    and not node.keywords
                ):
                    yield ctx.violation(
                        self, node,
                        ".result() without timeout inside a remote task/actor "
                        "method risks a nested-get deadlock",
                    )


# --------------------------------------------------------------------- RL002


@register
class BlockingCallInAsync(Rule):
    id = "RL002"
    name = "blocking-call-in-async"
    description = (
        "Synchronous blocking call inside an async def. One blocked "
        "coroutine stalls every request multiplexed onto the actor's event "
        "loop. Use the asyncio equivalent or loop.run_in_executor()."
    )

    _BLOCKING = {
        "time.sleep": "await asyncio.sleep(...)",
        "subprocess.run": "asyncio.create_subprocess_exec(...)",
        "subprocess.call": "asyncio.create_subprocess_exec(...)",
        "subprocess.check_call": "asyncio.create_subprocess_exec(...)",
        "subprocess.check_output": "asyncio.create_subprocess_exec(...)",
        "socket.create_connection": "asyncio.open_connection(...)",
        "urllib.request.urlopen": "an async HTTP client or run_in_executor",
        "requests.get": "an async HTTP client or run_in_executor",
        "requests.post": "an async HTTP client or run_in_executor",
        "requests.request": "an async HTTP client or run_in_executor",
        "os.system": "asyncio.create_subprocess_shell(...)",
        "ray_tpu.get": "await the ref or run_in_executor",
        "ray.get": "await the ref or run_in_executor",
    }

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            # nested async defs are walked on their own; nested SYNC defs
            # are skipped — the rule's own remedy is to move the blocking
            # call into a sync helper handed to loop.run_in_executor, and
            # that fix must lint clean
            for cur in _walk_scope(node):
                if not isinstance(cur, ast.Call):
                    continue
                d = dotted_name(cur.func)
                if d in self._BLOCKING:
                    yield ctx.violation(
                        self, cur,
                        f"blocking {d}() inside async def {node.name}; "
                        f"use {self._BLOCKING[d]}",
                    )


# --------------------------------------------------------------------- RL003


@register
class UnserializableCapture(Rule):
    id = "RL003"
    name = "unserializable-closure-capture"
    description = (
        "A @remote function closes over a name bound to a known-"
        "unserializable constructor (lock, socket, file handle, ...). "
        "Submission will fail in cloudpickle with an opaque error; create "
        "the resource inside the task or move it to an actor."
    )

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        ctors = known_unserializable_calls()

        def unserializable_bindings(scope: ast.AST) -> dict:
            """name -> dotted ctor for ``name = threading.Lock()``-style
            assignments directly in ``scope`` (not in nested defs)."""
            out: dict = {}
            body = scope.body if hasattr(scope, "body") else []
            stack = list(body)
            while stack:
                cur = stack.pop()
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(cur, ast.Assign) and isinstance(cur.value, ast.Call):
                    d = dotted_name(cur.value.func)
                    if d in ctors:
                        for tgt in cur.targets:
                            if isinstance(tgt, ast.Name):
                                out[tgt.id] = d
                stack.extend(ast.iter_child_nodes(cur))
            return out

        for node in ast.walk(ctx.tree):
            if not is_remote_def(node) or isinstance(node, ast.ClassDef):
                continue
            # enclosing lexical scopes, nearest first
            enclosing = [
                a for a in ctx.ancestors(node)
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module))
            ]
            env: dict = {}
            for scope in reversed(enclosing):  # outermost first; inner shadows
                env.update(unserializable_bindings(scope))
            if not env:
                continue
            local = {a.arg for a in node.args.args + node.args.kwonlyargs}
            if node.args.vararg:
                local.add(node.args.vararg.arg)
            if node.args.kwarg:
                local.add(node.args.kwarg.arg)
            for cur in _walk_scope(node):
                if isinstance(cur, ast.Name) and isinstance(cur.ctx, ast.Store):
                    local.add(cur.id)
            for cur in _walk_scope(node):
                if (
                    isinstance(cur, ast.Name)
                    and isinstance(cur.ctx, ast.Load)
                    and cur.id not in local
                    and cur.id in env
                ):
                    yield ctx.violation(
                        self, cur,
                        f"@remote function {node.name} captures {cur.id!r} "
                        f"bound to {env[cur.id]}() "
                        f"({ctors[env[cur.id]]}); it cannot be serialized",
                    )


# --------------------------------------------------------------------- RL004


@register
class MutableDefaultOnActorMethod(Rule):
    id = "RL004"
    name = "mutable-default-arg"
    description = (
        "Mutable default argument on an actor method or @remote function. "
        "Actor methods are long-lived: the shared default accumulates state "
        "across calls and across restarts inconsistently. Use None + init."
    )

    _CTOR_NAMES = {"list", "dict", "set"}

    def _mutable_defaults(self, node) -> Iterator[ast.AST]:
        defaults = list(node.args.defaults) + [d for d in node.args.kw_defaults if d]
        for d in defaults:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                yield d
            elif (
                isinstance(d, ast.Call)
                and isinstance(d.func, ast.Name)
                and d.func.id in self._CTOR_NAMES
            ):
                yield d

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        seen = set()
        for scope in ctx.remote_scopes():
            seen.add(scope)
            for d in self._mutable_defaults(scope):
                yield ctx.violation(
                    self, d,
                    f"mutable default argument on {ctx.qualname(scope)}; "
                    "use None and initialize inside",
                )
        for node in ast.walk(ctx.tree):
            if is_remote_def(node) and node not in seen:
                for d in self._mutable_defaults(node):
                    yield ctx.violation(
                        self, d,
                        f"mutable default argument on @remote {node.name}; "
                        "use None and initialize inside",
                    )


# --------------------------------------------------------------------- RL005


@register
class InconsistentLockOrder(Rule):
    id = "RL005"
    name = "inconsistent-lock-order"
    description = (
        "Two methods of the same class acquire the same pair of locks in "
        "opposite nesting order (via with-statements). Under concurrency "
        "that is an ABBA deadlock. Pick one global order per class."
    )

    # anchored on a word start so 'clock'/'block'/'unlock' don't match
    _LOCK_ATTR_RE = re.compile(r"(?:^|_)(lock|rlock|mutex|cv|cond)s?$", re.I)

    def _lock_key(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and self._LOCK_ATTR_RE.search(expr.attr):
                return f"self.{expr.attr}"
        elif isinstance(expr, ast.Name) and self._LOCK_ATTR_RE.search(expr.id):
            return expr.id
        return None

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            # pair -> (method name, With node) of first sighting
            order: dict = {}
            reported = set()
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for pair, node in self._nested_pairs(meth):
                    order.setdefault(pair, (meth.name, node))
            for (outer, inner), (meth_name, node) in order.items():
                rev = (inner, outer)
                key = frozenset((outer, inner))
                if rev in order and key not in reported:
                    reported.add(key)
                    other = order[rev][0]
                    yield ctx.violation(
                        self, node,
                        f"{meth_name} acquires {outer} then {inner}, but "
                        f"{other} acquires {inner} then {outer} "
                        "(ABBA deadlock risk)",
                    )

    def _nested_pairs(self, meth) -> Iterator[tuple]:
        """(outer, inner) lock-name pairs from nested with-statements,
        depth-first with an explicit held-lock stack."""

        def visit(node, held):
            for cur in ast.iter_child_nodes(node):
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(cur, (ast.With, ast.AsyncWith)):
                    acquired = []
                    for item in cur.items:
                        k = self._lock_key(item.context_expr)
                        if k is not None:
                            for h in held + acquired:
                                if h != k:
                                    pairs.append(((h, k), cur))
                            acquired.append(k)
                    visit(cur, held + acquired)
                else:
                    visit(cur, held)

        pairs: list = []
        visit(meth, [])
        return iter(pairs)


# --------------------------------------------------------------------- RL006


@register
class HostSyncInHotLoop(Rule):
    id = "RL006"
    name = "host-sync-in-hot-loop"
    description = (
        "Device-to-host synchronization (.block_until_ready(), "
        "jax.device_get, np.asarray/np.array on device values) inside a "
        "loop in a hot path (ops/, train/, rl/, rlhf/). Each call stalls "
        "the XLA pipeline; hoist out of the loop or batch with "
        "jax.device_get on the whole pytree once."
    )

    HOT_DIRS = ("ops", "train", "rl", "rlhf", "llm")
    _SYNC_NAMES = {
        "jax.device_get",
        "np.asarray",
        "np.array",
        "numpy.asarray",
        "numpy.array",
        "jax.block_until_ready",
    }

    def _in_hot_path(self, ctx: FileContext) -> bool:
        parts = ctx.display_path.split("/")
        return any(d in parts for d in self.HOT_DIRS)

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not self._in_hot_path(ctx):
            return

        rule = self
        out: list = []

        class V(ast.NodeVisitor):
            def __init__(self):
                self.loop_depth = 0

            def visit_For(self, node):
                self.loop_depth += 1
                self.generic_visit(node)
                self.loop_depth -= 1

            visit_While = visit_For

            def visit_Call(self, node):
                if rule._is_sync(node) and self.loop_depth > 0:
                    out.append(
                        ctx.violation(
                            rule, node,
                            f"host sync {rule._label(node)} inside a loop in "
                            "a hot path; hoist it out or batch the transfer",
                        )
                    )
                self.generic_visit(node)

        V().visit(ctx.tree)
        yield from out

    def _is_sync(self, call: ast.Call) -> bool:
        d = dotted_name(call.func)
        if d in self._SYNC_NAMES:
            return True
        return isinstance(call.func, ast.Attribute) and call.func.attr == "block_until_ready"

    def _label(self, call: ast.Call) -> str:
        return dotted_name(call.func) or f".{call.func.attr}()"


# --------------------------------------------------------------------- RL007


@register
class SwallowedExceptionInLoop(Rule):
    id = "RL007"
    name = "swallowed-exception-in-loop"
    description = (
        "except:/except Exception: with a body of only pass/continue inside "
        "a loop. In a daemon loop this silently discards every failure "
        "forever — the classic invisible-outage bug. Log the exception "
        "(even throttled) before continuing."
    )

    _BROAD = {"Exception", "BaseException"}

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        if isinstance(t, ast.Name) and t.id in self._BROAD:
            return True
        if isinstance(t, ast.Tuple):
            return any(isinstance(e, ast.Name) and e.id in self._BROAD for e in t.elts)
        return False

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node):
                continue
            if not all(isinstance(s, (ast.Pass, ast.Continue)) for s in node.body):
                continue
            in_loop = False
            for anc in ctx.ancestors(node):
                if isinstance(anc, (ast.While, ast.For, ast.AsyncFor)):
                    in_loop = True
                    break
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    break  # the loop must be in the same scope
            if in_loop:
                yield ctx.violation(
                    self, node,
                    "broad except swallowing every error inside a loop; log "
                    "the exception (throttled) before continuing",
                )


# --------------------------------------------------------------------- RL008


@register
class ActorInitIOWithoutTimeout(Rule):
    id = "RL008"
    name = "actor-init-io-without-timeout"
    description = (
        "Actor __init__ performs network / subprocess IO with no timeout. "
        "Actor creation blocks the caller's first method call and holds a "
        "worker slot; a hung dependency turns into a hung cluster. Add a "
        "timeout or defer the IO to a ready() method."
    )

    _NEEDS_TIMEOUT = {
        "socket.create_connection",
        "urllib.request.urlopen",
        "requests.get",
        "requests.post",
        "requests.request",
        "subprocess.run",
        "subprocess.call",
        "subprocess.check_call",
        "subprocess.check_output",
    }

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for cls in ast.walk(ctx.tree):
            if not is_actor_class(cls):
                continue
            init = next(
                (
                    s for s in cls.body
                    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and s.name == "__init__"
                ),
                None,
            )
            if init is None:
                continue
            for node in _walk_scope(init):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                if d in self._NEEDS_TIMEOUT:
                    # socket.create_connection's 2nd positional is the timeout
                    if _has_timeout(node) or (
                        d == "socket.create_connection" and len(node.args) >= 2
                    ):
                        continue
                    yield ctx.violation(
                        self, node,
                        f"{d}() in actor __init__ without timeout=; a hung "
                        "peer blocks actor creation and pins a worker slot",
                    )
                elif isinstance(node.func, ast.Attribute) and node.func.attr == "connect":
                    yield ctx.violation(
                        self, node,
                        ".connect() in actor __init__; set a socket timeout "
                        "first or defer to a ready() method",
                    )
