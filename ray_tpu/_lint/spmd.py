"""raylint phase 2.1: the mesh / sharding / Pallas-contract model
(RL020-RL024).

The ROADMAP's next subsystem — the KV pool and paged attention sharded
over a ``tp`` mesh with shard_map + Pallas remote DMA — multiplies the
SPMD surface the way PR 14 multiplied the concurrency surface. The
costliest bugs on that surface are silent: PR 13's true positive
(``shard_train_state`` placing ``step`` with ``SingleDeviceSharding``
against the mesh, recompiling fwd+bwd every train step) produced no
exception, only a 2x step time. This module mechanizes that review over
the sites the index recorded:

* **Axis-binding environments (RL020)** — every ``shard_map``/``pmap``
  jit site contributes its mesh's axis names to the functions that can
  execute under it: the resolved traced target AND the site's owner
  (nested-def bodies fold their collectives into the owner scope). A
  site whose mesh cannot be statically resolved contributes the ANY
  marker, which suppresses the rule for that function — a rule can
  miss, it must not invent. A collective's literal axis fires when the
  function's allowed set (own env ∪ direct callers' envs, one level)
  is ANY-free and lacks the axis. Collectives whose axis is a
  parameter are promoted to the CALLER: a caller passing a literal
  axis (or relying on a literal default) fires at its call site when
  both the callee's and the caller's allowed sets are ANY-free and
  lack the axis.
* **Spec/mesh drift (RL021)** — ``P(...)`` literals reachable from a
  shard_map site's in_specs/out_specs (through local ``name = P(...)``
  binds) and ``NamedSharding(mesh, P(...))`` pairings are checked
  against the mesh's resolved axis universe; ``in_specs`` tuple arity
  is checked against the traced target's visible parameter span
  (functools.partial pre-bound positions/keywords shrink it, defaults
  widen the lower bound); a placement whose ``P(...)`` names more dims
  than its literal-rank operand has fires at the placement.
* **Pallas contracts (RL022)** — index_map arity must equal grid rank
  (+ num_scalar_prefetch when the grid came from a
  PrefetchScalarGridSpec — scalar-prefetch operands are prepended to
  every index_map); an out-block shape that provably does not divide a
  literal out_shape dim with no masking evidence (``pl.when`` / a
  mask-named identifier in the resolved kernel) fires; and
  interpret-GATED kernel wrappers must be declared in a module-level
  ``INTERPRET_ONLY`` registry. A wrapper is gated when its pallas site
  hardcodes ``interpret=True``, or when a dispatcher in the module
  both calls it and branches on the site's interpret gate call as an
  un-negated disjunct (``if _interpret() or ...: return xla_path``) —
  i.e. the module routes AWAY from the compiled path exactly where CI
  runs, so the kernel's production path has zero validation coverage.
  The registry is verified bidirectionally: a gated wrapper missing
  from it fires, and a stale entry naming no gated wrapper fires, so
  un-gating a kernel forces the declared debt to be retired with it.
* **Remote-DMA pairing (RL023)** — a ``make_async_remote_copy`` handle
  whose ``.start()`` has a path to exit (exception edges included)
  skipping ``.wait()`` leaves a semaphore permanently unsignaled on
  the peer chip — the next DMA on that semaphore deadlocks the mesh,
  far from the cause. RL015's Acquisition machinery applied to DMA
  handles: ``.wait()``/``.wait_send()``/``.wait_recv()`` release,
  hand-off/return/``with`` transfer ownership.
* **Sharding drift (RL024)** — a value bound from a ``device_put``
  with no sharding operand (committed to the default device) or an
  explicit ``SingleDeviceSharding``, flowing into a registry-resolved
  jitted call whose matching positional ``in_shardings`` entry is a
  ``NamedSharding``, fires at the placement site: every such call
  re-lays-out the operand and retraces — the PR 13 bug class, static.

Precision choices (documented under-approximations — each can miss,
none can invent):

* A shard_map whose mesh expression does not resolve to literal axis
  names (parameter meshes — ``pipeline.py``, ``train_step.py``,
  ``sharding.py``) yields the ANY environment, suppressing RL020/RL021
  axis checks for everything under it.
* Nested-def shard_map bodies credit the OWNER scope's whole env, so
  owner-scope collectives outside the body also get credit (over-
  approximation in the safe direction).
* Param-axis promotion only reads keyword arguments and literal
  defaults at caller sites; positional axis operands are not promoted.
* ``in_specs`` arity is only checked when the spec is a literal
  tuple/list and the traced target resolves with no vararg/kwarg.
* RL022 treats ``wait_send`` alone as a full release (miss direction);
  divisibility only fires on literal out_shape dims vs literal
  out-block dims with no masking evidence in the resolved kernel.
* RL024 requires the placed value to be BOUND to a name and passed as
  that bare name, in the same function, placement before call in
  source order; a later re-placement of the same name with a
  NamedSharding clears it. Comprehension-internal placements
  (learner.py's fetch loop) have no bound name and are skipped.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple

from ray_tpu._lint.dataflow import (
    Acquisition,
    calls_in,
    resource_leaks,
    scope_stmts,
)
from ray_tpu._lint.index import (
    FuncInfo,
    JitSite,
    PallasSite,
    PlacementSite,
    ProjectIndex,
    _kw_expr,
    _spec_entries,
    dotted_parts,
)


class _Any:
    """Unresolvable binding environment — suppresses, never fires."""

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "ANY"


ANY = _Any()

#: DMA handle release methods; ``wait_send`` alone is treated as a full
#: release (documented miss-direction under-approximation — splitting
#: send/recv waits across paths is a deliberate overlap idiom)
DMA_RELEASES = ("wait", "wait_send", "wait_recv")


# --------------------------------------------------------------- mesh axes


def _module_scope(index: ProjectIndex, module: str) -> Optional[FuncInfo]:
    mi = index.modules.get(module)
    return mi.scope if mi is not None else None


def _axes_of_names_expr(
    expr: Optional[ast.AST], module: str, index: ProjectIndex
) -> Optional[Tuple[str, ...]]:
    """An ``axis_names`` operand -> literal axis tuple: string/tuple
    literals, ``tuple(NAME)`` unwrapping, module string-tuple globals
    (``AXES``) with one import-following hop."""
    if expr is None:
        return None
    if isinstance(expr, ast.Call):
        d = dotted_parts(expr.func)
        if d and d[-1] == "tuple" and len(expr.args) == 1:
            expr = expr.args[0]
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return (expr.value,)
    if isinstance(expr, (ast.Tuple, ast.List)):
        if expr.elts and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in expr.elts
        ):
            return tuple(e.value for e in expr.elts)
        return None
    if isinstance(expr, ast.Name):
        mi = index.modules.get(module)
        if mi is None:
            return None
        got = mi.str_tuples.get(expr.id)
        if got:
            return got
        tgt = mi.imports.get(expr.id)
        if tgt and "." in tgt:
            mod, _, name = tgt.rpartition(".")
            tmi = index.modules.get(mod)
            if tmi is not None:
                return tmi.str_tuples.get(name)
    return None


def _axes_of_ctor(
    call: ast.Call, info: FuncInfo, index: ProjectIndex
) -> Optional[Tuple[str, ...]]:
    """``Mesh(arr, axis_names)`` / ``make_*mesh(...)`` -> axis names.
    Factories resolve through the call graph to their ``axis_names``
    keyword-only default when the call site doesn't override it."""
    d = dotted_parts(call.func)
    if not d:
        return None
    last = d[-1]
    if last == "Mesh":
        ax = _kw_expr(call, "axis_names")
        if ax is None and len(call.args) >= 2:
            ax = call.args[1]
        return _axes_of_names_expr(ax, info.module, index)
    if last.startswith("make_") and last.endswith("mesh"):
        ax = _kw_expr(call, "axis_names")
        if ax is not None:
            return _axes_of_names_expr(ax, info.module, index)
        callee = index.resolve_call(info, d)
        if callee is None:
            return None
        args = getattr(callee.node, "args", None)
        if args is None:
            return None
        for kwonly, default in zip(args.kwonlyargs, args.kw_defaults):
            if kwonly.arg == "axis_names" and default is not None:
                return _axes_of_names_expr(default, callee.module, index)
    return None


def mesh_axes(
    index: ProjectIndex, info: FuncInfo, expr: Optional[ast.AST]
) -> Optional[Tuple[str, ...]]:
    """A mesh expression -> its axis-name tuple, or None (unresolvable:
    parameter meshes, attribute chains the index can't anchor)."""
    if expr is None:
        return None
    if isinstance(expr, ast.Call):
        return _axes_of_ctor(expr, info, index)
    chain = dotted_parts(expr)
    if not chain:
        return None
    return _axes_of_chain(index, info, chain)


def _axes_of_chain(
    index: ProjectIndex, info: FuncInfo, chain: Tuple[str, ...]
) -> Optional[Tuple[str, ...]]:
    if len(chain) == 1:
        if chain[0] in info.param_names:
            return None
        for scope in (info, _module_scope(index, info.module)):
            if scope is None:
                continue
            for mb in scope.mesh_binds:
                if chain[0] in mb.names:
                    got = _axes_of_ctor(mb.node, scope, index)
                    if got is not None:
                        return got
        return None
    if (
        info.self_name
        and chain[0] == info.self_name
        and info.cls is not None
        and len(chain) == 2
    ):
        for _in_init, _kind, value in info.cls.attr_assigns.get(chain[1], []):
            if isinstance(value, ast.Call):
                got = _axes_of_ctor(value, info, index)
                if got is not None:
                    return got
    return None


# --------------------------------------------------------------- the model


@dataclasses.dataclass(frozen=True)
class CollectiveHit:
    """RL020: a literal collective axis no enclosing mesh binds."""

    op: str
    axis: str
    node: ast.AST
    info: FuncInfo
    via: Optional[str] = None      # callee desc when promoted to a caller


@dataclasses.dataclass(frozen=True)
class SpecHit:
    """RL021: one spec/mesh drift finding."""

    kind: str                      # 'axis' | 'arity' | 'rank'
    node: ast.AST
    info: FuncInfo
    detail: str


@dataclasses.dataclass(frozen=True)
class PallasHit:
    """RL022: one Pallas contract finding."""

    kind: str                      # 'arity' | 'divide' | 'undeclared' | 'stale' | 'reasonless'
    node: ast.AST
    info: Optional[FuncInfo]       # None for registry-anchored findings
    ctx: object                    # FileContext for the anchor
    detail: str


@dataclasses.dataclass(frozen=True)
class PlacementHit:
    """RL024: a single-device placement feeding a NamedSharding slot."""

    placement: PlacementSite
    call_node: ast.Call
    jit_label: str
    pos: int
    info: FuncInfo


class SpmdModel:
    """Whole-program mesh/sharding model, built once per lint run."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        #: FuncInfo.key -> set of axis names bound by some enclosing
        #: shard_map/pmap, or ANY when a binding site's mesh is opaque
        self.envs: Dict[str, object] = {}
        #: FuncInfo.key -> [(caller FuncInfo, CallSite), ...]
        self.callers: Dict[str, List[Tuple[FuncInfo, object]]] = {}
        self._allowed_cache: Dict[str, object] = {}
        self._build_envs()
        self._build_callers()

    # -- environments ------------------------------------------------------

    def _build_envs(self) -> None:
        for site, owner in self.index.jit_sites:
            wrappers = site.wrappers()
            if not ({"shard_map", "pmap"} & wrappers):
                continue
            axes: set = set()
            opaque = False
            if "shard_map" in wrappers:
                got = mesh_axes(self.index, owner, site.mesh_expr)
                if got is None:
                    opaque = True
                else:
                    axes |= set(got)
            if "pmap" in wrappers:
                if site.axis_name:
                    axes |= set(site.axis_name)
                else:
                    opaque = True
            keys = {owner.key}
            tgt = self.index.resolve_jit_target(site, owner)
            if tgt is not None:
                keys.add(tgt.key)
            for key in keys:
                if opaque or self.envs.get(key) is ANY:
                    self.envs[key] = ANY
                else:
                    cur = self.envs.setdefault(key, set())
                    cur |= axes

    def _build_callers(self) -> None:
        for info in self.index.functions.values():
            for cs in info.calls:
                callee = self.index.resolve_call(info, cs.chain)
                if callee is not None and callee.key != info.key:
                    self.callers.setdefault(callee.key, []).append((info, cs))

    def allowed(self, key: str) -> object:
        """Axis names possibly bound when ``key`` runs: own env ∪ every
        direct caller's env (one level). ANY anywhere poisons the set."""
        got = self._allowed_cache.get(key)
        if got is not None:
            return got
        base = self.envs.get(key)
        if base is ANY:
            self._allowed_cache[key] = ANY
            return ANY
        out = set(base or ())
        for caller, _cs in self.callers.get(key, ()):
            env = self.envs.get(caller.key)
            if env is ANY:
                self._allowed_cache[key] = ANY
                return ANY
            out |= env or set()
        self._allowed_cache[key] = out
        return out

    # -- RL020 -------------------------------------------------------------

    def collective_violations(self) -> List[CollectiveHit]:
        hits: List[CollectiveHit] = []
        for info in self.index.functions.values():
            if not info.collectives:
                continue
            al = self.allowed(info.key)
            for c in info.collectives:
                if c.axes:
                    if al is ANY:
                        continue
                    for ax in c.axes:
                        if ax not in al:
                            hits.append(CollectiveHit(c.op, ax, c.node, info))
                elif c.axis_param:
                    hits.extend(self._promote_param_axis(info, c, al))
        return hits

    def _promote_param_axis(
        self, info: FuncInfo, c, al: object
    ) -> List[CollectiveHit]:
        """A collective whose axis is ``info``'s parameter: fire at a
        caller passing a literal axis (or relying on a literal default)
        when neither side's allowed set can bind it."""
        if al is ANY:
            return []
        default = _param_default_axis(info, c.axis_param)
        hits: List[CollectiveHit] = []
        for caller, cs in self.callers.get(info.key, ()):
            ag = self.allowed(caller.key)
            if ag is ANY:
                continue
            passed = _kw_expr(cs.node, c.axis_param)
            if passed is None:
                axes = default
            elif isinstance(passed, ast.Constant) and isinstance(
                passed.value, str
            ):
                axes = (passed.value,)
            else:
                continue               # dynamic / positional: not promoted
            if axes is None:
                continue
            for ax in axes:
                if ax not in al and ax not in ag:
                    hits.append(
                        CollectiveHit(
                            c.op, ax, cs.node, caller,
                            via=f"{info.qualname}({c.axis_param}=...)",
                        )
                    )
        return hits

    # -- RL021 -------------------------------------------------------------

    def spec_violations(self) -> List[SpecHit]:
        hits: List[SpecHit] = []
        for site, owner in self.index.jit_sites:
            if "shard_map" not in site.wrappers():
                continue
            axes = mesh_axes(self.index, owner, site.mesh_expr)
            if axes is not None:
                universe = set(axes)
                for spec_expr in (site.in_specs, site.out_specs):
                    for p_call in _spec_calls(spec_expr, owner):
                        hits.extend(
                            _axis_drift(p_call, universe, axes, owner)
                        )
            hits.extend(self._arity_drift(site, owner))
        for info in self.index.functions.values():
            for ns in info.named_shardings:
                if ns.spec is None or ns.mesh_chain is None:
                    continue
                axes = _axes_of_chain(self.index, info, ns.mesh_chain)
                if axes is None:
                    continue
                hits.extend(_axis_drift(ns.spec, set(axes), axes, info))
            for p in info.placements:
                if (
                    p.spec_rank is not None
                    and p.operand_rank is not None
                    and p.spec_rank > p.operand_rank
                ):
                    hits.append(
                        SpecHit(
                            "rank", p.node, info,
                            f"PartitionSpec names {p.spec_rank} dims but the "
                            f"placed operand has rank {p.operand_rank}",
                        )
                    )
        return hits

    def _arity_drift(self, site: JitSite, owner: FuncInfo) -> List[SpecHit]:
        """len(in_specs) vs the traced target's visible parameter span."""
        spec = site.in_specs
        if not isinstance(spec, (ast.Tuple, ast.List)):
            return []
        target = self.index.resolve_jit_target(site, owner)
        if target is None:
            return []
        args = getattr(target.node, "args", None)
        if args is None or args.vararg or args.kwarg:
            return []
        params = [a.arg for a in args.args]
        if params and params[0] == "self":
            params = params[1:]
        defaulted = set(params[len(params) - len(args.defaults):])
        bound_kw = set(site.partial_kw) & set(params)
        visible = [
            p
            for i, p in enumerate(params)
            if i >= site.partial_pos and p not in bound_kw
        ]
        hi = len(visible)
        lo = hi - len([p for p in visible if p in defaulted])
        n = len(spec.elts)
        if lo <= n <= hi:
            return []
        want = str(hi) if lo == hi else f"{lo}..{hi}"
        return [
            SpecHit(
                "arity", spec, owner,
                f"in_specs has {n} entries but {target.qualname} takes "
                f"{want} argument(s) after partial binding",
            )
        ]

    # -- RL022 -------------------------------------------------------------

    def pallas_violations(self) -> List[PallasHit]:
        hits: List[PallasHit] = []
        by_module: Dict[str, Dict[str, FuncInfo]] = {}
        for info in self.index.functions.values():
            for ps in info.pallas_sites:
                hits.extend(_pallas_shape_checks(self.index, info, ps))
                if _site_gated(self.index, info, ps):
                    by_module.setdefault(info.module, {})[
                        info.qualname.rsplit(".", 1)[-1]
                    ] = info
        declared: Dict[str, list] = {}
        for module, entries, anchor, ctx in self.index.interpret_only_decls():
            declared.setdefault(module, []).append((entries, anchor, ctx))
        for module in set(by_module) | set(declared):
            gated = by_module.get(module, {})
            names_declared: set = set()
            for entries, anchor, ctx in declared.get(module, ()):
                for entry in entries:
                    name, _, reason = entry.partition(":")
                    name = name.strip()
                    if not reason.strip():
                        hits.append(
                            PallasHit(
                                "reasonless", anchor, None, ctx,
                                f"INTERPRET_ONLY entry {entry!r} has no "
                                "justification — spell it "
                                "'<wrapper>: <why the compiled path is "
                                "unexercised>'",
                            )
                        )
                    names_declared.add(name)
                    if name not in gated:
                        hits.append(
                            PallasHit(
                                "stale", anchor, None, ctx,
                                f"INTERPRET_ONLY entry {entry!r} matches no "
                                "interpret-gated pallas wrapper in this "
                                "module — the kernel was un-gated (or "
                                "renamed); retire the entry with the debt",
                            )
                        )
            for name, info in gated.items():
                if name not in names_declared:
                    hits.append(
                        PallasHit(
                            "undeclared", info.node, info, info.ctx,
                            f"{name} is an interpret-gated pallas wrapper "
                            "(its compiled path is routed around wherever "
                            "the gate is on) but is not declared in this "
                            "module's INTERPRET_ONLY registry",
                        )
                    )
        return hits

    # -- RL023 -------------------------------------------------------------

    def dma_acquisitions(self, info: FuncInfo) -> List[Acquisition]:
        """``h = make_async_remote_copy(...)`` handles -> Acquisitions
        anchored at their ``h.start()`` calls, for resource_leaks."""
        acqs: List[Acquisition] = []
        for name, _bind in info.dma_binds:
            for stmt in scope_stmts(info.node):
                for call in calls_in(stmt):
                    d = dotted_parts(call.func)
                    if d == (name, "start"):
                        acqs.append(
                            Acquisition(
                                call=call,
                                label=f"{name}.start",
                                release_methods=DMA_RELEASES,
                                receiver=(name,),
                                tracked_roots=(name,),
                            )
                        )
        return acqs

    # -- RL024 -------------------------------------------------------------

    def drift_violations(self, cache) -> List[PlacementHit]:
        hits: List[PlacementHit] = []
        for info in self.index.functions.values():
            if not info.placements or not info.calls:
                continue
            sources = [
                p
                for p in info.placements
                if p.sharding in ("absent", "single") and p.bound_names
            ]
            if not sources:
                continue
            local_jits = cache._local_jit_names(info)
            for cs in info.calls:
                got = cache._direct_site(info, cs.node, local_jits)
                if got is None:
                    continue
                site, label = got
                named_pos = _named_sharding_positions(site, info)
                if not named_pos:
                    continue
                for i, arg in enumerate(cs.node.args):
                    if i not in named_pos or not isinstance(arg, ast.Name):
                        continue
                    for p in sources:
                        if (
                            arg.id in p.bound_names
                            and p.node.lineno < cs.node.lineno
                            and not _replaced_named(
                                info, arg.id, p.node.lineno, cs.node.lineno
                            )
                        ):
                            hits.append(
                                PlacementHit(p, cs.node, label, i, info)
                            )
        return hits


# ------------------------------------------------------------ rule helpers


def _param_default_axis(
    info: FuncInfo, pname: str
) -> Optional[Tuple[str, ...]]:
    args = getattr(info.node, "args", None)
    if args is None:
        return None
    pos = [a.arg for a in args.args]
    if pname in pos:
        i = pos.index(pname) - (len(pos) - len(args.defaults))
        dflt = args.defaults[i] if i >= 0 else None
    else:
        dflt = None
        for kwonly, d in zip(args.kwonlyargs, args.kw_defaults):
            if kwonly.arg == pname:
                dflt = d
    if isinstance(dflt, ast.Constant) and isinstance(dflt.value, str):
        return (dflt.value,)
    return None


def _spec_calls(expr: Optional[ast.AST], info: FuncInfo) -> List[ast.Call]:
    """P(...) literals reachable from an in_specs/out_specs expression:
    the expression itself, tuple/list elements, and local names bound to
    a P(...) literal earlier in the scope."""
    if expr is None:
        return []
    elems = expr.elts if isinstance(expr, (ast.Tuple, ast.List)) else [expr]
    out: List[ast.Call] = []
    for e in elems:
        if isinstance(e, ast.Name):
            bound = info.spec_locals.get(e.id)
            if bound is not None:
                out.append(bound)
        elif isinstance(e, ast.Call):
            d = dotted_parts(e.func)
            if d and d[-1] in ("P", "PartitionSpec"):
                out.append(e)
    return out


def _axis_drift(
    p_call: ast.Call, universe: set, axes: Tuple[str, ...], info: FuncInfo
) -> List[SpecHit]:
    hits: List[SpecHit] = []
    for entry in _spec_entries(p_call):
        named = entry if isinstance(entry, tuple) else (entry,)
        for ax in named:
            if isinstance(ax, str) and ax not in ("?", "*") and ax not in universe:
                hits.append(
                    SpecHit(
                        "axis", p_call, info,
                        f"PartitionSpec names axis {ax!r} but its mesh "
                        f"only has axes {tuple(axes)!r}",
                    )
                )
    return hits


def _pallas_shape_checks(
    index: ProjectIndex, info: FuncInfo, ps: PallasSite
) -> List[PallasHit]:
    hits: List[PallasHit] = []
    if ps.grid_rank is not None:
        expected = ps.grid_rank + (
            ps.num_scalar_prefetch if ps.scalar_grid else 0
        )
        for bs in ps.block_specs:
            if bs.index_map_arity is not None and bs.index_map_arity != expected:
                hits.append(
                    PallasHit(
                        "arity", bs.node, info, info.ctx,
                        f"BlockSpec index_map takes {bs.index_map_arity} "
                        f"args but the grid has rank {ps.grid_rank}"
                        + (
                            f" plus {ps.num_scalar_prefetch} scalar-prefetch "
                            "operand(s)"
                            if ps.scalar_grid and ps.num_scalar_prefetch
                            else ""
                        )
                        + f" — index_map must take {expected}",
                    )
                )
    if ps.out_shape_dims is not None:
        for bs in ps.block_specs:
            if bs.role != "out" or bs.block_shape is None:
                continue
            if len(bs.block_shape) != len(ps.out_shape_dims):
                continue
            for blk, dim in zip(bs.block_shape, ps.out_shape_dims):
                if (
                    isinstance(blk, int)
                    and isinstance(dim, int)
                    and blk > 0
                    and dim % blk
                    and not _kernel_masks(index, info, ps)
                ):
                    hits.append(
                        PallasHit(
                            "divide", bs.node, info, info.ctx,
                            f"out BlockSpec dim {blk} does not divide the "
                            f"out_shape dim {dim} and the kernel shows no "
                            "masking (pl.when / mask) — the tail block "
                            "reads/writes out of bounds",
                        )
                    )
    return hits


def _kernel_masks(index: ProjectIndex, info: FuncInfo, ps: PallasSite) -> bool:
    """Masking evidence in the resolved kernel body: a ``pl.when`` call
    or any mask-named identifier."""
    if ps.kernel_chain is None:
        return False
    kernel = index.resolve_call(info, ps.kernel_chain)
    if kernel is None:
        return False
    for node in ast.walk(kernel.node):
        if isinstance(node, ast.Call):
            d = dotted_parts(node.func)
            if d and d[-1] == "when":
                return True
        if isinstance(node, ast.Name) and "mask" in node.id.lower():
            return True
    return False


def _site_gated(index: ProjectIndex, info: FuncInfo, ps: PallasSite) -> bool:
    """True when this pallas site's compiled path is routed around:
    interpret=True hardcoded, or a same-module dispatcher calls this
    wrapper AND branches on the site's gate call as an un-negated
    disjunct (``if _interpret() or ...: return xla_path``)."""
    if ps.interpret == "true":
        return True
    if ps.interpret != "dynamic" or ps.interpret_chain is None:
        return False
    mi = index.modules.get(info.module)
    if mi is None:
        return False
    wrapper = info.qualname.rsplit(".", 1)[-1]
    for fn in mi.functions.values():
        if fn.key == info.key:
            continue
        if not any(cs.chain and cs.chain[-1] == wrapper for cs in fn.calls):
            continue
        for node in ast.walk(fn.node):
            if isinstance(node, ast.If) and _gate_disjunct(
                node.test, ps.interpret_chain
            ):
                return True
    return False


def _gate_disjunct(test: ast.AST, gate: Tuple[str, ...]) -> bool:
    """The gate call appears un-negated as the test or an Or-disjunct
    (``not gate() and ...`` does NOT match — that routes TOWARD the
    compiled path off-gate, i.e. the kernel keeps interpret coverage)."""
    stack = [test]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.BoolOp) and isinstance(n.op, ast.Or):
            stack.extend(n.values)
        elif isinstance(n, ast.Call) and dotted_parts(n.func) == gate:
            return True
    return False


def _named_sharding_positions(site: JitSite, info: FuncInfo) -> set:
    """Positional indices of ``in_shardings`` entries that are
    NamedSharding constructions (or local names bound to one)."""
    shard = site.in_shardings
    if shard is None:
        return set()
    entries = (
        list(shard.elts) if isinstance(shard, (ast.Tuple, ast.List)) else [shard]
    )
    out = set()
    for i, e in enumerate(entries):
        if isinstance(e, ast.Call):
            d = dotted_parts(e.func)
            if d and d[-1] == "NamedSharding":
                out.add(i)
        elif isinstance(e, ast.Name) and e.id in info.named_sharding_locals:
            out.add(i)
    return out


def _replaced_named(
    info: FuncInfo, name: str, after_line: int, before_line: int
) -> bool:
    """A later placement rebinding ``name`` WITH a NamedSharding between
    the flagged placement and the call clears the drift (linear source-
    order approximation)."""
    for p in info.placements:
        if (
            p.sharding == "named"
            and name in p.bound_names
            and after_line < p.node.lineno < before_line
        ):
            return True
    return False


def get_model(index: ProjectIndex) -> SpmdModel:
    model = getattr(index, "_spmd_model", None)
    if model is None:
        model = SpmdModel(index)
        index._spmd_model = model
    return model
