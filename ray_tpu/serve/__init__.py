"""ray_tpu.serve: online model serving on the task/actor runtime.

Reference: ``python/ray/serve/`` (65.8k LoC) — the capability surface here:
``@serve.deployment`` + ``.bind()`` + ``serve.run`` (api.py), controller
reconciliation into replica actors (controller.py / replica.py), handle-side
power-of-two-choices routing (handle.py), ``@serve.batch`` coalescing
(batching.py — the TPU-critical piece: concurrent requests meet the jitted
model as ONE batch), queue-depth autoscaling, composition via handles, and
an HTTP JSON ingress (proxy.py).
"""

from ray_tpu.serve.api import (  # noqa: F401
    Application,
    Deployment,
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    run,
    run_config,
    shutdown,
    status,
)
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.multiplex import (  # noqa: F401
    get_multiplexed_model_id,
    multiplexed,
)
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse  # noqa: F401
from ray_tpu.serve._private.common import AutoscalingConfig  # noqa: F401
