"""serve.llm: stream-first LLM serving on top of ``ray_tpu.llm``.

``LLMDeployment`` runs one ``LLMEngine`` inside each replica: a daemon
thread turns the engine crank while replica request threads submit and
stream.  ``__call__`` is a GENERATOR, so the serve stack's existing
streaming-generator machinery does the rest — callers use

    handle = serve.run(build_llm_app(model="gptj", model_cfg=cfg))
    for tok in handle.options(stream=True).remote([1, 2, 3],
                                                  max_tokens=32):
        ...

and tokens cross the cluster as they are sampled (TTFT ≈ one prefill +
one decode step, not the whole completion).  ``generate`` is the
blocking whole-completion method for non-streaming callers (a generator
return can't pickle through ``handle_request``).

Autoscaling: the replica exports the engine's queue depth and KV-cache
utilization — through ``util.metrics`` gauges (``llm_*`` series) and
through ``autoscaling_metrics()``, which the serve controller's scaling
decision CONSUMES (``_private/controller.desired_replicas``: queued
requests count as load; a KV-saturated replica adds upscale pressure).
Since a continuous-batching replica absorbs many concurrent requests per
slot set, ongoing-request counts alone under-report saturation; queue
depth (> 0 means the engine is admission-bound) and KV utilization
(≈ 1.0 means preemption-bound) are the honest signals.
"""

from __future__ import annotations

import threading
from typing import Optional

from ray_tpu.llm.engine import EngineConfig, LLMEngine
from ray_tpu.llm.scheduler import SamplingParams


def _build_model(model: str, model_cfg, params, seed: int):
    """Materialize (cfg, params) inside the replica — shipping a seed
    instead of a parameter pytree keeps deployment specs small and lets
    each replica initialize straight onto its own device."""
    import jax

    if model == "gptj":
        from ray_tpu.models.gptj import GPTJ_6B, GPTJConfig, gptj_init

        cfg = model_cfg or GPTJ_6B
        if not isinstance(cfg, GPTJConfig):
            raise TypeError(f"model_cfg must be a GPTJConfig, got {type(cfg).__name__}")
        if params is None:
            params = gptj_init(jax.random.PRNGKey(seed), cfg)
    elif model == "gpt":
        from ray_tpu.models.gpt import GPTConfig, gpt_init

        cfg = model_cfg or GPTConfig()
        if not isinstance(cfg, GPTConfig):
            raise TypeError(f"model_cfg must be a GPTConfig, got {type(cfg).__name__}")
        if params is None:
            params = gpt_init(jax.random.PRNGKey(seed), cfg)
    else:
        raise ValueError(f"unknown model family {model!r}; expected 'gptj' or 'gpt'")
    return cfg, params


class LLMDeployment:
    """The replica callable. Decorate/bind via ``build_llm_app`` (or apply
    ``serve.deployment`` yourself for custom replica options)."""

    def __init__(
        self,
        model: str = "gptj",
        model_cfg=None,
        params: Optional[dict] = None,
        engine_config: Optional[EngineConfig] = None,
        seed: int = 0,
        warmup: bool = True,
        stream_timeout_s: float = 300.0,
        draft_model_cfg=None,
        draft_params: Optional[dict] = None,
    ):
        cfg, params = _build_model(model, model_cfg, params, seed)
        # speculative decoding with the small-model drafter
        # (engine_config.spec_drafter == "model"): the draft model's
        # config + params pass straight through to the engine; the
        # default n-gram drafter needs neither
        if draft_model_cfg is not None and draft_params is None:
            _, draft_params = _build_model(
                model, draft_model_cfg, None, seed
            )
        #: max wait for the next streamed token — must cover the ADMISSION
        #: wait of a request queued behind a saturated engine, not just
        #: inter-token gaps (the engine's own 60s default is too tight for
        #: a deployment whose whole point is absorbing a deep queue)
        self._stream_timeout_s = stream_timeout_s
        self._engine = LLMEngine(
            cfg, params, engine_config,
            draft_model_cfg=draft_model_cfg, draft_params=draft_params,
        )
        # per-engine watchdog (llm.watchdog): stall detection, wedge-proof
        # deadline/cancel reaping, KV-pool leak audit — a serving replica
        # always runs one
        self._engine.start_watchdog()
        if warmup:
            # compile the prefill/decode/verify/sampling jits NOW, inside
            # replica creation, so serve.run's readiness gate covers
            # compile time and the first real request streams at
            # steady-state latency (covers BOTH decode paths of a
            # speculating engine — see LLMEngine.warmup)
            self._engine.warmup()
        self._stop = threading.Event()
        self._loop = threading.Thread(
            target=self._engine.run_loop, args=(self._stop,),
            name="llm-engine-loop", daemon=True,
        )
        self._loop.start()

    # -- request path ------------------------------------------------------

    def __call__(
        self,
        prompt: list,
        max_tokens: int = 16,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        stop_token_ids: tuple = (),
        seed: int = 0,
        deadline_s: Optional[float] = None,
        resume_tokens: tuple = (),
    ):
        """Streaming generation: yields token ids as the engine samples
        them. Call with ``handle.options(stream=True)``; the generator
        shape is what routes this through ``handle_request_streaming``.

        ``prompt`` may also be a dict — ``{"prompt": [...], "max_tokens":
        32, "temperature": 0.8, ...}`` — so HTTP callers (whose JSON body
        arrives as the single positional payload) can set sampling knobs
        and a ``deadline_s``; dict keys override the keyword defaults.

        ``resume_tokens`` is the mid-stream failover journal (the handle
        layer injects it via the deployment's ``stream_resume_arg``
        contract): tokens a dead replica already delivered. Generation
        continues AFTER them, token-identically (``LLMEngine.submit``).
        A dict payload's own ``resume_tokens`` (a client-side resume)
        concatenates with the handle-injected journal.
        """
        if isinstance(prompt, dict):
            body = dict(prompt)
            try:
                prompt = body.pop("prompt")
            except KeyError:
                raise ValueError("dict payload requires a 'prompt' key") from None
            max_tokens = body.pop("max_tokens", max_tokens)
            temperature = body.pop("temperature", temperature)
            top_k = body.pop("top_k", top_k)
            top_p = body.pop("top_p", top_p)
            stop_token_ids = body.pop("stop_token_ids", stop_token_ids)
            seed = body.pop("seed", seed)
            deadline_s = body.pop("deadline_s", deadline_s)
            # client-resumed prefix first, then the failover journal
            resume_tokens = tuple(body.pop("resume_tokens", ())) + tuple(
                resume_tokens
            )
            if body:
                raise ValueError(f"unknown payload keys: {sorted(body)}")
        params = SamplingParams(
            max_tokens=max_tokens,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            stop_token_ids=tuple(stop_token_ids),
            seed=seed,
        )
        req = self._engine.submit(
            [int(t) for t in prompt], params, deadline_s,
            resume_tokens=tuple(int(t) for t in resume_tokens),
        )
        # with an explicit deadline the engine itself ends the stream at
        # the deadline; the get-timeout only needs to outlast it
        timeout = (
            deadline_s + 5.0 if deadline_s is not None else self._stream_timeout_s
        )
        try:
            yield from self._engine.stream_tokens(req, timeout=timeout)
        finally:
            # consumer walked away (stream closed/replica thread unwinding):
            # stop generating for nobody
            if not req.finished:
                self._engine.cancel(req.id)

    def generate(self, prompt: list, **kwargs) -> list:
        """Blocking whole-completion variant for non-streaming callers."""
        return list(self.__call__(prompt, **kwargs))

    # -- control plane -----------------------------------------------------

    def update_weights(self, update, version=None, timeout: float = 120.0) -> int:
        """Versioned weight hot-swap — the SAME push path raw actor
        engines use (``rlhf.sync.apply_weight_update`` →
        ``LLMEngine.update_weights``): accepts a published
        ``rlhf.sync.WeightUpdate`` manifest (chunked object-plane refs)
        or a raw params pytree + ``version``, and applies it between
        engine steps WITHOUT draining in-flight streams. An RLHF learner
        can therefore push to serve-hosted inference replicas and
        dedicated rollout actors with one code path:

            handle.update_weights.remote(weight_update).result()

        Routes like any other handle call (one replica per call); push
        once per replica — or use ``num_replicas=1`` engines for rollout
        duty — when every replica must advance."""
        from ray_tpu.rlhf.sync import WeightUpdate, apply_weight_update

        if not isinstance(update, WeightUpdate):
            # version=None lets LLMEngine.update_weights bump UNDER its
            # lock — computing current+1 here would race a concurrent
            # push into two different param sets sharing one version
            update = (update, version)
        return apply_weight_update(self._engine, update, timeout=timeout)

    def weights_version(self) -> int:
        return self._engine.weights_version

    def autoscaling_metrics(self) -> dict:
        """Saturation signals for replica autoscaling: ``queue_depth``
        (admission-bound) and ``kv_utilization`` (memory-bound) on top of
        the running count the controller already polls.
        ``prefix_hit_rate`` rides along informationally — the
        cross-request prefix cache (``llm.prefix_cache``) is per-replica,
        so routing that keeps a tenant's traffic on one replica (session
        affinity, a ROADMAP item) shows up directly as a higher hit rate
        here.  Note ``kv_utilization`` counts only blocks live requests
        hold: cache-only residents are evictable on demand and never
        create upscale pressure.  Under tensor parallelism (``tp > 1``)
        it stays POOL-WIDE, not per-shard: block ids are global across
        the mesh (llm.multichip), so the pool-wide fraction IS each
        device's fraction and the honest saturation signal."""
        s = self._engine.stats()
        m = {
            "queue_depth": s["queue_depth"],
            "kv_utilization": s["kv_utilization"],
            "running": s["running"],
            "waiting": s["waiting"],
        }
        if "prefix_cache" in s:
            m["prefix_hit_rate"] = s["prefix_cache"]["hit_rate"]
        return m

    def stats(self) -> dict:
        return self._engine.stats()

    def check_health(self) -> None:
        if not self._loop.is_alive():
            raise RuntimeError("LLM engine loop thread died")

    def __del__(self):
        try:
            self._stop.set()
        except Exception:  # raylint: disable=RL007
            pass  # interpreter teardown: the daemon thread dies with us


def build_llm_app(
    model: str = "gptj",
    model_cfg=None,
    engine_config: Optional[EngineConfig] = None,
    seed: int = 0,
    num_replicas: int = 1,
    max_ongoing_requests: int = 16,
    autoscaling_config=None,
    name: str = "LLMDeployment",
    warmup: bool = True,
    tp: Optional[int] = None,
):
    """Bind an ``LLMDeployment`` application (deploy with ``serve.run``).

    ``tp`` — tensor parallelism per replica (``llm.multichip``): a
    convenience overlay on ``engine_config.tp`` so app builders can
    shard replicas over the tp mesh without constructing an
    ``EngineConfig``.  Each replica builds its own mesh over the first
    ``tp`` visible devices.  ``autoscaling_metrics`` keeps reporting the
    POOL-WIDE ``kv_utilization`` — the block ledger is host-global under
    tp (every device holds the same blocks' local heads), so a per-shard
    number would just repeat it ``tp`` times and a partial one would
    under-report saturation to the controller.

    ``max_ongoing_requests`` should comfortably exceed the engine's
    ``max_slots`` — the whole point of continuous batching is holding
    more concurrent streams than decode slots and letting the engine's
    queue absorb the difference (queue depth then drives autoscaling).

    ``warmup=True`` (default) compiles inside replica ``__init__`` so the
    readiness gate covers jit time and first requests stream at
    steady-state latency. ``warmup=False`` trades that for FAST replica
    (re)join: a replacement replica becomes routable in seconds and pays
    compile inside its first request — the right trade when replicas
    churn (chaos, spot preemption) and a mid-stream failover must find a
    routable successor before the router's pick deadline, not after a
    full warmup.
    """
    from ray_tpu.serve.api import deployment

    if tp is not None:
        import dataclasses

        engine_config = dataclasses.replace(
            engine_config or EngineConfig(), tp=tp
        )
    dep = deployment(
        LLMDeployment,
        name=name,
        num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
        autoscaling_config=autoscaling_config,
        # mid-stream failover contract: a stream whose replica dies is
        # re-submitted with resume_tokens=<delivered tokens> and resumes
        # token-identically; deadline_s is re-submitted MINUS the time
        # already spent, so failovers never extend a client's declared
        # wait budget (RESILIENCE.md)
        stream_resume_arg="resume_tokens",
        stream_deadline_arg="deadline_s",
    )
    return dep.bind(
        model=model, model_cfg=model_cfg, engine_config=engine_config,
        seed=seed, warmup=warmup,
    )
