"""DeploymentHandle: the client-side request path.

Reference: ``serve/handle.py:830`` (DeploymentHandle / DeploymentResponse),
``_private/router.py:36,326`` (Router.assign_request) and
``_private/replica_scheduler/pow_2_scheduler.py:44`` (power-of-two-choices:
sample two replicas, pick the one with the shorter queue). The router keeps
a local in-flight count per replica (updated at submit/complete) and
refreshes its replica set from the controller when the controller's version
counter moves — the long-poll-lite equivalent of the reference's
LongPollHost.

Handles pickle cleanly (they carry only the deployment name): deployment
composition passes handles through replica init args, and any process that
can reach the named controller actor can route.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Optional

from ray_tpu.serve._private.common import CONTROLLER_NAME


class DeploymentResponse:
    """Future-like wrapper over the underlying ObjectRef.

    If the backing replica died before producing a result, ``result()``
    re-routes the request once through a fresh replica (the reference
    router's retry-on-replica-failure semantics).
    """

    def __init__(self, ref, router: "_Router", replica_idx: int, retry=None, replica=None):
        self._ref = ref
        self._router = router
        self._replica_idx = replica_idx
        self._replica = replica
        self._retry = retry  # zero-arg callable re-submitting the request
        self._done = False

    @staticmethod
    def max_retries() -> int:  # tunable: serve_handle_max_retries
        from ray_tpu._private.config import GLOBAL_CONFIG

        return GLOBAL_CONFIG.serve_handle_max_retries

    def result(self, timeout: Optional[float] = None) -> Any:
        import ray_tpu
        from ray_tpu.exceptions import RayActorError

        try:
            return ray_tpu.get(self._ref, timeout=timeout)
        except RayActorError:
            self._settle()
            if self._replica is not None:
                # fail over immediately: this router stops routing to the
                # dead replica without waiting for the controller's health
                # check to notice
                self._router.mark_failed(self._replica)
            else:
                self._router.drop()
            if self._retry is None:
                raise  # retry budget exhausted — surface the failure
            # no sleep: pick() itself waits (with deadline) when no live
            # replica is available; with others alive the retry is instant
            return self._retry().result(timeout)
        finally:
            self._settle()

    def _to_object_ref(self):
        """Pass-through so responses can feed other task/actor calls."""
        self._settle()
        return self._ref

    # -- async completion protocol (used by the HTTP proxy resolver) -------
    # The slot stays held until _async_done/_async_failed so admission
    # accounting and pow-2 balancing see async requests exactly like
    # blocking result() callers.

    def _async_ref(self):
        """The ref to await WITHOUT settling the router slot."""
        return self._ref

    def _async_done(self) -> None:
        self._settle()

    def _async_failed(self, exc) -> "Optional[DeploymentResponse]":
        """Mirror ``result()``'s failover: on replica death, mark it failed
        and return a freshly-routed response to keep awaiting (may block in
        pick() — call from a worker thread, not an event loop). Returns None
        when ``exc`` should surface to the caller."""
        from ray_tpu.exceptions import RayActorError

        self._settle()
        if not isinstance(exc, RayActorError):
            return None
        if self._replica is not None:
            self._router.mark_failed(self._replica)
        else:
            self._router.drop()
        if self._retry is None:
            return None
        return self._retry()

    def _settle(self):
        if not self._done:
            self._done = True
            self._router._complete(self._replica_idx)


class _Router:
    """Per-handle replica set + pow-2 picker."""

    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name
        self._lock = threading.Lock()
        self._replicas: list = []
        self._inflight: list[int] = []
        self._max_ongoing = 1
        self._version = -1
        self._poll_thread: Optional[threading.Thread] = None
        self._closed = False
        # replicas observed dead by THIS router, excluded until the
        # controller publishes a new replica set — immediate failover
        # instead of waiting out the controller's health-check window
        self._excluded: set = set()
        self._excluded_version = -1
        self._real_version = -1  # last version actually seen from the
        # controller — unlike _version it is never reset by drop(), so
        # exclusion bookkeeping survives cache invalidation
        # mid-stream failover contract, fetched from the controller once
        # per router (False = not yet fetched; None = deployment has none)
        self._resume_arg: "object" = False

    def _controller(self):
        import ray_tpu

        return ray_tpu.get_actor(CONTROLLER_NAME)

    def _apply(self, version, replicas, max_ongoing) -> None:
        with self._lock:
            self._max_ongoing = max_ongoing
            if version != self._version:
                self._version = version
                self._replicas = replicas
                self._inflight = [0] * len(replicas)
            self._real_version = version
            if self._excluded and version != self._excluded_version:
                # the controller published a NEW replica set since the
                # exclusions were recorded — they no longer apply
                self._excluded.clear()

    def _refresh(self, force: bool = False):
        """One synchronous pull — used at router birth and after drop()
        (observed replica death). Steady-state updates arrive PUSHED via
        the long-poll thread; nothing here runs per request."""
        import ray_tpu

        with self._lock:
            if not force and self._replicas:
                return
        version, replicas, max_ongoing = ray_tpu.get(
            self._controller().get_replicas.remote(self.deployment_name), timeout=30
        )
        self._apply(version, replicas, max_ongoing)
        with self._lock:
            start = self._poll_thread is None
            if start:  # under the lock: concurrent first requests must not
                # each park a long-poll on the controller's thread budget
                self._poll_thread = threading.Thread(
                    target=self._poll_loop, name="serve-router-longpoll", daemon=True
                )
        if start:
            self._poll_thread.start()

    def _poll_loop(self):
        """Long-poll push (reference: _private/long_poll.py client): one
        outstanding poll_replicas call parks on the controller until the
        config version moves — router updates arrive without any periodic
        version polling."""
        import ray_tpu

        while not self._closed:
            try:
                version, replicas, max_ongoing = ray_tpu.get(
                    self._controller().poll_replicas.remote(
                        self.deployment_name, self._real_version, 25.0
                    ),
                    timeout=40,
                )
                self._apply(version, replicas, max_ongoing)
            except Exception:
                if self._closed:
                    return
                time.sleep(0.5)  # controller briefly unreachable: back off

    def _sticky_pick(self, model_id: str, live: list) -> int:
        """Highest-random-weight over STABLE replica identities: a model's
        home replica doesn't move when unrelated replicas join/die/exclude
        (positional hashing would remap models on every live-set change)."""
        import hashlib

        def weight(i):
            key = str(self._replica_key(self._replicas[i]))
            return int.from_bytes(
                hashlib.sha1(f"{model_id}:{key}".encode()).digest()[:8], "little"
            )

        return max(live, key=weight)

    def pick(self, model_id: Optional[str] = None) -> tuple[Any, int]:
        """Power-of-two-choices over local in-flight counts, honoring the
        per-replica max_ongoing_requests admission cap (backpressure —
        reference: pow_2_scheduler queue-length caps). Multiplexed requests
        route by rendezvous hash so a model id sticks to one replica
        (reference: model-aware multiplex routing)."""
        deadline = time.time() + 30.0
        while True:
            self._refresh()
            with self._lock:
                live = [
                    i
                    for i in range(len(self._replicas))
                    if self._replica_key(self._replicas[i]) not in self._excluded
                ]
                n = len(live)
                if n:
                    if model_id:
                        # sticky: wait for THE model's replica rather than
                        # spilling onto others (a spill would duplicate the
                        # model's weights in another replica's HBM)
                        idx = self._sticky_pick(model_id, live)
                        if self._inflight[idx] < self._max_ongoing:
                            self._inflight[idx] += 1
                            return self._replicas[idx], idx
                        idx = None
                    elif n == 1:
                        idx = live[0]
                    else:
                        i, j = random.sample(live, 2)
                        idx = i if self._inflight[i] <= self._inflight[j] else j
                    if idx is not None and self._inflight[idx] < self._max_ongoing:
                        self._inflight[idx] += 1
                        return self._replicas[idx], idx
                    if idx is not None:
                        # chosen replica at capacity: try the live minimum
                        idx = min(live, key=self._inflight.__getitem__)
                        if self._inflight[idx] < self._max_ongoing:
                            self._inflight[idx] += 1
                            return self._replicas[idx], idx
            if time.time() > deadline:
                raise RuntimeError(
                    f"No replica capacity for deployment {self.deployment_name!r}"
                )
            time.sleep(0.02)

    @staticmethod
    def _replica_key(handle):
        return getattr(handle, "_actor_id", None) or id(handle)

    def stream_contract(self):
        """The deployment's mid-stream-failover contract —
        ``(resume_arg, deadline_arg)`` or None (RESILIENCE.md) — cached
        after one controller RPC."""
        if self._resume_arg is False:
            import ray_tpu

            try:
                got = ray_tpu.get(
                    self._controller().get_stream_resume_arg.remote(
                        self.deployment_name
                    ),
                    timeout=30,
                )
                self._resume_arg = tuple(got) if got is not None else None
            except Exception:
                return None  # controller briefly unreachable: retry next call
        return self._resume_arg

    def free_capacity(self) -> Optional[int]:
        """Admission slots open across live replicas right now — the
        proxy's deadline-aware shed probe. None when the replica set is
        unknown (never shed on no evidence)."""
        with self._lock:
            if not self._replicas:
                return None
            live = [
                i
                for i in range(len(self._replicas))
                if self._replica_key(self._replicas[i]) not in self._excluded
            ]
            if not live:
                return None
            return sum(
                max(0, self._max_ongoing - self._inflight[i]) for i in live
            )

    def mark_failed(self, replica):
        """Exclude a replica this router saw die — routing fails over NOW,
        before the controller's health check notices."""
        with self._lock:
            self._excluded.add(self._replica_key(replica))
            self._excluded_version = self._real_version
        self.drop()

    def _complete(self, idx: int):
        with self._lock:
            if 0 <= idx < len(self._inflight) and self._inflight[idx] > 0:
                self._inflight[idx] -= 1

    def drop(self):
        """Force-refresh after a replica failure."""
        with self._lock:
            self._version = -1
            self._replicas = []


class StreamingDeploymentResponse:
    """Iterates a streaming deployment call's items as they are produced
    (reference: serve's streaming DeploymentResponse over ASGI). Wraps the
    ObjectRefGenerator from ``num_returns="streaming"``; the router's
    in-flight slot is held until the stream is exhausted or closed.

    Mid-stream failover (RESILIENCE.md): when the deployment declares a
    ``stream_resume_arg``, ``resume`` is a callable re-submitting the
    request to a fresh replica with the items delivered so far — on
    replica death the iterator journals what it already yielded, fails
    over, and CONTINUES yielding from the successor stream in place, so
    the consumer sees one uninterrupted, token-exact stream. Without a
    resume contract, replica death raises (the pre-existing behavior)."""

    def __init__(self, gen, router: "_Router", replica_idx: int, replica=None,
                 resume=None):
        self._gen = gen
        self._router = router
        self._replica_idx = replica_idx
        self._replica = replica
        self._resume = resume  # callable(items so far) -> successor response
        self._done = False

    def __iter__(self):
        import ray_tpu
        from ray_tpu.exceptions import RayActorError

        cur = self
        # items yielded since the CURRENT attempt began; the journal of
        # earlier attempts lives in the resume closure's kwargs (each
        # failover bakes its prefix into the next call's resume kwarg, so
        # re-journaling it here would double-count)
        emitted: list = []
        try:
            while True:
                try:
                    for ref in cur._gen:
                        item = ray_tpu.get(ref, timeout=60)
                        emitted.append(item)
                        yield item
                    return
                except RayActorError:
                    # replica died mid-stream: tell the router NOW so new
                    # requests fail over immediately (mirrors
                    # DeploymentResponse.result)
                    if cur._replica is not None:
                        cur._router.mark_failed(cur._replica)
                    else:
                        cur._router.drop()
                    if cur._resume is None:
                        raise  # no resume contract / budget exhausted
                    nxt = cur._resume(list(emitted))
                    cur.close()
                    cur = nxt
                    emitted = []
        finally:
            cur.close()

    def close(self) -> None:
        if not self._done:
            self._done = True
            self._router._complete(self._replica_idx)
            try:
                self._gen.close()
            except Exception:
                pass


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._remote(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(
        self,
        deployment_name: str,
        _model_id: Optional[str] = None,
        _stream: bool = False,
        _resume: bool = True,
    ):
        self.deployment_name = deployment_name
        self._router: Optional[_Router] = None
        self._model_id = _model_id
        self._stream = _stream
        self._resume = _resume

    def options(
        self,
        *,
        multiplexed_model_id: Optional[str] = None,
        stream: Optional[bool] = None,
        resume: Optional[bool] = None,
    ) -> "DeploymentHandle":
        """A view of this handle with request options (reference:
        ``handle.options(multiplexed_model_id=..., stream=...)``). The view
        SHARES the router (in-flight accounting stays coherent).
        ``stream=True`` makes ``.remote()`` return a
        StreamingDeploymentResponse yielding items as the replica's
        generator produces them. ``resume=False`` opts a streaming call out
        of mid-stream failover even when the deployment declares a
        ``stream_resume_arg`` (replica death then raises, the pre-resume
        behavior)."""
        view = DeploymentHandle(
            self.deployment_name,
            _model_id=multiplexed_model_id if multiplexed_model_id is not None else self._model_id,
            _stream=self._stream if stream is None else stream,
            _resume=self._resume if resume is None else resume,
        )
        view._router = self._get_router()
        return view

    # picklability: the router (with live actor handles) stays local
    def __getstate__(self):
        return {
            "deployment_name": self.deployment_name,
            "_model_id": self._model_id,
            "_stream": self._stream,
            "_resume": self._resume,
        }

    def __setstate__(self, state):
        self.deployment_name = state["deployment_name"]
        self._model_id = state.get("_model_id")
        self._stream = state.get("_stream", False)
        self._resume = state.get("_resume", True)
        self._router = None

    def _get_router(self) -> _Router:
        if self._router is None:
            self._router = _Router(self.deployment_name)
        return self._router

    def free_capacity(self) -> Optional[int]:
        """Open admission slots across live replicas (None = replica set
        unknown) — the proxy's deadline-aware shed probe."""
        return self._get_router().free_capacity()

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._remote("__call__", args, kwargs)

    def __getattr__(self, name: str) -> _MethodCaller:
        if name.startswith("_") or name in ("deployment_name", "options"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def _remote(
        self, method: str, args: tuple, kwargs: dict, _retries: Optional[int] = None
    ) -> DeploymentResponse:
        from ray_tpu.exceptions import RayActorError

        if _retries is None:
            _retries = DeploymentResponse.max_retries()
        router = self._get_router()
        # unwrap nested responses so composition chains pass values not refs
        args = tuple(a.result() if isinstance(a, DeploymentResponse) else a for a in args)
        kwargs = {
            k: (v.result() if isinstance(v, DeploymentResponse) else v)
            for k, v in kwargs.items()
        }
        # bounded budget: a request that kills every replica it touches must
        # eventually surface its RayActorError, not loop forever
        retry = (
            (lambda: self._remote(method, args, kwargs, _retries - 1))
            if _retries > 0
            else None
        )
        # mid-stream failover: when the deployment declares a resume kwarg,
        # the streaming response journals delivered items and re-submits to
        # a fresh replica on death — the next attempt's resume kwarg carries
        # this attempt's kwarg prefix plus everything newly delivered, so
        # repeated failovers chain without re-sending or double-counting
        resume = None
        if self._stream and self._resume and _retries > 0:
            contract = router.stream_contract()
            if contract is not None:
                resume_arg, deadline_arg = contract
                prior = list(kwargs.get(resume_arg) or ())
                t_attempt = time.monotonic()

                def resume(emitted, _r=_retries):
                    kw = dict(kwargs)
                    kw[resume_arg] = prior + list(emitted)
                    # the client's deadline budget spans the WHOLE request:
                    # hand the successor only what remains of this
                    # attempt's relative deadline (chained failovers each
                    # decrement their own attempt's spend, so the budget
                    # composes instead of resetting per replica death)
                    if deadline_arg is not None:
                        d = kw.get(deadline_arg)
                        if isinstance(d, (int, float)) and d > 0:
                            spent = time.monotonic() - t_attempt
                            kw[deadline_arg] = max(0.05, d - spent)
                    return self._remote(method, args, kw, _r - 1)

        for attempt in range(3):
            replica, idx = router.pick(model_id=self._model_id)
            try:
                if self._stream:
                    gen = replica.handle_request_streaming.options(
                        num_returns="streaming"
                    ).remote(method, args, kwargs, self._model_id)
                    return StreamingDeploymentResponse(
                        gen, router, idx, replica=replica, resume=resume
                    )
                if self._model_id:
                    ref = replica.handle_request.remote(
                        method, args, kwargs, self._model_id
                    )
                else:
                    ref = replica.handle_request.remote(method, args, kwargs)
                return DeploymentResponse(ref, router, idx, retry=retry, replica=replica)
            except RayActorError:
                router._complete(idx)
                router.mark_failed(replica)
        raise RuntimeError(f"Could not submit to deployment {self.deployment_name!r}")
