"""Serve public API: ``@serve.deployment``, ``bind``, ``serve.run``.

Reference: ``python/ray/serve/api.py:246`` (deployment decorator), ``:439``
(serve.run). An ``Application`` is a bound deployment graph — ``.bind()``
arguments may themselves be Applications, and ``serve.run`` materializes the
graph bottom-up, injecting DeploymentHandles where child apps appear
(model-composition, reference ``serve/handle.py``).
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Optional, Union

import ray_tpu
from ray_tpu.serve._private.common import (
    CONTROLLER_NAME,
    AutoscalingConfig,
    DeploymentConfig,
    DeploymentSpec,
)
from ray_tpu.serve.handle import DeploymentHandle

def _default_http_port() -> int:  # tunable: serve_http_port
    from ray_tpu._private.config import GLOBAL_CONFIG

    return GLOBAL_CONFIG.serve_http_port


def _wrap_function(fn: Callable) -> type:
    """Function deployments become single-method callables. A generator
    function keeps its generator-ness (the wrapper yields from it) so
    streaming detection in _collect_specs sees through the wrapper."""
    if inspect.isgeneratorfunction(fn):

        class _GenFuncDeployment:
            def __call__(self, *args, **kwargs):
                yield from fn(*args, **kwargs)

        _GenFuncDeployment.__name__ = getattr(fn, "__name__", "func")
        return _GenFuncDeployment

    class _FuncDeployment:
        def __call__(self, *args, **kwargs):
            return fn(*args, **kwargs)

    _FuncDeployment.__name__ = getattr(fn, "__name__", "func")
    return _FuncDeployment


@dataclasses.dataclass
class Deployment:
    """The decorated (not yet bound) deployment."""

    callable_cls: type
    name: str
    config: DeploymentConfig

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def options(self, **kwargs) -> "Deployment":
        new_cfg = dataclasses.replace(self.config)
        name = kwargs.pop("name", self.name)
        for k, v in kwargs.items():
            if k == "autoscaling_config" and isinstance(v, dict):
                v = AutoscalingConfig(**v)
            if not hasattr(new_cfg, k):
                raise TypeError(f"Unknown deployment option {k!r}")
            setattr(new_cfg, k, v)
        return Deployment(self.callable_cls, name, new_cfg)


class Application:
    """A deployment bound to init args; args may nest other Applications."""

    def __init__(self, deployment: Deployment, args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


def deployment(
    _cls: Optional[Union[type, Callable]] = None,
    *,
    name: Optional[str] = None,
    num_replicas: Optional[Union[int, str]] = None,
    max_ongoing_requests: int = 8,
    user_config: Any = None,
    autoscaling_config: Optional[Union[dict, AutoscalingConfig]] = None,
    ray_actor_options: Optional[dict] = None,
    health_check_period_s: float = 1.0,
    graceful_shutdown_timeout_s: float = 10.0,
    grpc_codec: str = "bytes",
    stream_resume_arg: Optional[str] = None,
    stream_deadline_arg: Optional[str] = None,
) -> Union[Deployment, Callable[..., Deployment]]:
    """Reference: ``serve/api.py:246``. ``num_replicas="auto"`` enables
    autoscaling with defaults. ``grpc_codec`` sets the gRPC ingress payload
    contract: "bytes" (verbatim passthrough, default), "pickle" (opt-in for
    trusted Python clients), or "json". ``stream_resume_arg`` names the
    kwarg that makes streaming calls RESUMABLE across replica death
    (``DeploymentConfig.stream_resume_arg``; serve.llm sets
    ``"resume_tokens"``)."""
    from ray_tpu.serve._private.grpc_proxy import CODECS

    if grpc_codec not in CODECS:
        raise ValueError(f"grpc_codec must be one of {CODECS}, got {grpc_codec!r}")

    def build(target) -> Deployment:
        cls = target if inspect.isclass(target) else _wrap_function(target)
        nonlocal autoscaling_config, num_replicas
        if num_replicas == "auto" and autoscaling_config is None:
            autoscaling_config = AutoscalingConfig()
        asc = (
            AutoscalingConfig(**autoscaling_config)
            if isinstance(autoscaling_config, dict)
            else autoscaling_config
        )
        cfg = DeploymentConfig(
            num_replicas=num_replicas if isinstance(num_replicas, int) else 1,
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config,
            autoscaling_config=asc,
            health_check_period_s=health_check_period_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            ray_actor_options=ray_actor_options or {},
            grpc_codec=grpc_codec,
            stream_resume_arg=stream_resume_arg,
            stream_deadline_arg=stream_deadline_arg,
        )
        return Deployment(cls, name or getattr(target, "__name__", "deployment"), cfg)

    if _cls is not None:
        return build(_cls)
    return build


# ---------------------------------------------------------------------------
# controller lifecycle + run
# ---------------------------------------------------------------------------


def _get_or_start_controller():
    from ray_tpu.serve._private.controller import ServeController

    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        cls = ray_tpu.remote(ServeController)
        # detached: the controller outlives any one handle (reference:
        # serve's controller is a detached named actor)
        controller = cls.options(
            name=CONTROLLER_NAME, get_if_exists=True, lifetime="detached",
            # generous: every router parks ONE long-poll here (long_poll
            # push, controller.poll_replicas) on top of regular control calls
            max_concurrency=256,
        ).remote()
        ray_tpu.get(controller.check_health.remote(), timeout=60)
        return controller


def _collect_specs(app: Application, app_name: str) -> tuple[list[DeploymentSpec], str]:
    """DFS the bind graph; nested Applications in args become handles."""
    specs: dict[int, DeploymentSpec] = {}
    names_used: dict[str, int] = {}

    def visit(node: Application) -> DeploymentHandle:
        key = id(node)
        if key in specs:
            return DeploymentHandle(specs[key].name)
        base = node.deployment.name
        n = names_used.get(base, 0)
        names_used[base] = n + 1
        dep_name = f"{app_name}_{base}" if n == 0 else f"{app_name}_{base}_{n}"

        def resolve(v):
            return visit(v) if isinstance(v, Application) else v

        args = tuple(resolve(a) for a in node.args)
        kwargs = {k: resolve(v) for k, v in node.kwargs.items()}
        cls = node.deployment.callable_cls
        call = getattr(cls, "__call__", None) if inspect.isclass(cls) else cls
        streaming = inspect.isgeneratorfunction(call) or inspect.isasyncgenfunction(call)
        specs[key] = DeploymentSpec(
            name=dep_name,
            app_name=app_name,
            callable_factory=cls,
            init_args=args,
            init_kwargs=kwargs,
            config=node.deployment.config,
            streaming=streaming,
        )
        return DeploymentHandle(dep_name)

    ingress_handle = visit(app)
    ordered = list(specs.values())
    # the root (first visited) is the ingress
    for s in ordered:
        s.is_ingress = s.name == ingress_handle.deployment_name
    return ordered, ingress_handle.deployment_name


def run(
    app: Application,
    name: str = "default",
    route_prefix: Optional[str] = None,
    http: bool = False,
    http_port: Optional[int] = None,
    grpc: bool = False,
    grpc_port: Optional[int] = None,
    _blocking: bool = True,
) -> DeploymentHandle:
    """Deploy an application; returns the ingress DeploymentHandle.

    Reference: ``serve/api.py:439``. ``http=True`` also ensures the HTTP
    proxy ingress is up (``GET/POST /<name>`` with a JSON body);
    ``grpc=True`` the gRPC ingress (``ray.serve.GenericService/Predict``
    with ``application`` metadata — see _private/grpc_proxy.py)."""
    import time

    controller = _get_or_start_controller()
    specs, ingress = _collect_specs(app, name)
    ray_tpu.get(controller.deploy_application.remote(name, specs), timeout=120)
    if http:
        if http_port is None:
            http_port = _default_http_port()
        ray_tpu.get(controller.ensure_proxy.remote(http_port), timeout=120)
    if grpc:
        ray_tpu.get(
            controller.ensure_grpc_proxy.remote(int(grpc_port or 0)), timeout=120
        )
    if _blocking:
        deadline = time.time() + 120
        while not ray_tpu.get(controller.ready.remote(), timeout=30):
            if time.time() > deadline:
                raise TimeoutError("Serve application failed to become ready")
            time.sleep(0.1)
    return DeploymentHandle(ingress)


def run_config(config: "dict | str", _blocking: bool = True) -> dict:
    """Declarative deploy from a config file/dict (reference:
    ``serve/schema.py`` ServeDeploySchema + ``serve deploy`` CLI).

    Schema::

        proxy:
          port: 8000                  # optional: enables the HTTP ingress
        applications:
          - name: app1
            import_path: pkg.mod:obj  # Application, Deployment, or builder
            args: {...}               # builder kwargs / Deployment.bind kwargs
            deployments:              # per-deployment config overrides
              - name: MyDeployment    # the @serve.deployment name
                num_replicas: 2
                max_ongoing_requests: 16

    ``config`` may be the dict itself, a path to a YAML/JSON file, or a YAML
    string. Returns ``{app_name: ingress_deployment_name}``.
    """
    import dataclasses as _dc
    import importlib
    import os

    if isinstance(config, str):
        text = None
        if os.path.exists(config):
            with open(config) as f:
                text = f.read()
        else:
            text = config
        try:
            import yaml

            config = yaml.safe_load(text)
        except ImportError:
            import json as _json

            config = _json.loads(text)
    if not isinstance(config, dict) or "applications" not in config:
        raise ValueError("serve config must be a mapping with an 'applications' list")

    handles: dict[str, str] = {}
    http_port = (config.get("proxy") or {}).get("port")
    for app_cfg in config["applications"]:
        app_name = app_cfg.get("name", "default")
        import_path = app_cfg["import_path"]
        mod_name, _, attr = import_path.partition(":")
        if not attr:
            raise ValueError(
                f"import_path {import_path!r} must be 'module.sub:attribute'"
            )
        obj = importlib.import_module(mod_name)
        for part in attr.split("."):
            obj = getattr(obj, part)
        args = app_cfg.get("args") or {}
        if isinstance(obj, Application):
            app = obj
        elif isinstance(obj, Deployment):
            app = obj.bind(**args)
        elif callable(obj):
            app = obj(**args)  # builder (reference: app builders with args)
            if isinstance(app, Deployment):
                app = app.bind()
        else:
            raise TypeError(
                f"{import_path!r} resolved to {type(obj).__name__}; expected an "
                "Application, Deployment, or builder callable"
            )
        if not isinstance(app, Application):
            raise TypeError(f"{import_path!r} did not produce an Application")

        controller = _get_or_start_controller()
        specs, ingress = _collect_specs(app, app_name)
        overrides = {
            d["name"]: d for d in app_cfg.get("deployments", []) if "name" in d
        }
        for spec in specs:
            base = spec.name[len(app_name) + 1 :]
            ov = overrides.get(base)
            if not ov:
                continue
            cfg = _dc.replace(spec.config)  # never mutate the shared Deployment config
            for k, v in ov.items():
                if k == "name":
                    continue
                if k == "autoscaling_config" and isinstance(v, dict):
                    v = AutoscalingConfig(**v)
                if not hasattr(cfg, k):
                    raise TypeError(f"Unknown deployment option {k!r} for {base!r}")
                setattr(cfg, k, v)
            spec.config = cfg
        ray_tpu.get(controller.deploy_application.remote(app_name, specs), timeout=120)
        handles[app_name] = ingress
    if http_port is not None:
        controller = _get_or_start_controller()
        ray_tpu.get(controller.ensure_proxy.remote(int(http_port)), timeout=120)
    if _blocking:
        import time

        controller = _get_or_start_controller()
        deadline = time.time() + 120
        while not ray_tpu.get(controller.ready.remote(), timeout=30):
            if time.time() > deadline:
                raise TimeoutError("Serve applications failed to become ready")
            time.sleep(0.1)
    return handles


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ingress = ray_tpu.get(controller.get_ingress.remote(name), timeout=30)
    if ingress is None:
        raise KeyError(f"No serve application named {name!r}")
    return DeploymentHandle(ingress)


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(f"{app_name}_{deployment_name}")


def status() -> dict:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    apps = ray_tpu.get(controller.list_apps.remote(), timeout=30)
    return {
        app: {
            d: ray_tpu.get(controller.get_deployment_status.remote(d), timeout=30)
            for d in deps
        }
        for app, deps in apps.items()
    }


def delete(name: str) -> None:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ray_tpu.get(controller.delete_application.remote(name), timeout=60)


def shutdown() -> None:
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=60)
        ray_tpu.kill(controller)
    except Exception:
        pass
