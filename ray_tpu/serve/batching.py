"""@serve.batch — opportunistic request batching inside a replica.

Reference: ``python/ray/serve/batching.py`` (_BatchQueue: requests enqueue,
a loop flushes up to max_batch_size after batch_wait_timeout_s). This is the
op that makes TPU serving fast: N concurrent single requests entering a
replica's thread pool coalesce into ONE jitted forward pass, so the MXU sees
a real batch dimension instead of N matmuls of batch 1.

Threaded implementation (replica concurrency is thread-based here, not
asyncio): callers enqueue (args, Future) and block on the Future; the first
waiter becomes the flusher — it waits until the batch fills or the timeout
lapses, calls the wrapped function ONCE with lists of arguments, and
distributes results/exceptions.
"""

from __future__ import annotations

import functools
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Optional


_CREATE_LOCK = threading.Lock()

#: batching metric family — RL012 cross-checks this registry against the
#: constructors in ``_metrics()`` and the observability docs
METRIC_NAMES = (
    "serve_batch_queue_depth",
    "serve_batch_last_flush_size",
)

_METRICS = None


def _metrics() -> dict:
    """Batching saturation gauges, created once per process (under
    _CREATE_LOCK: concurrent first submissions must not register duplicate
    global gauges, which would fight in metrics collect()). The same
    signal surface the LLM deployment exports: queue depth says the
    replica is admission-bound, flush size says how full the batches the
    MXU actually sees are — both tagged per batched function so replica
    autoscaling (and Grafana) can tell WHICH entry point saturates."""
    global _METRICS
    if _METRICS is None:
        with _CREATE_LOCK:
            if _METRICS is not None:
                return _METRICS
            from ray_tpu.util.metrics import Gauge

            _METRICS = {
                "depth": Gauge(
                    "serve_batch_queue_depth",
                    "requests waiting in a @serve.batch queue",
                    ("fn", "model"),
                ),
                "flush": Gauge(
                    "serve_batch_last_flush_size",
                    "batch size of the most recent flush",
                    ("fn", "model"),
                ),
            }
    return _METRICS


class _BatchQueue:
    def __init__(
        self,
        fn: Callable,
        max_batch_size: int,
        batch_wait_timeout_s: float,
        name: str = "",
        model_id: str = "",
    ):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.name = name or getattr(fn, "__name__", "batch")
        # multiplexed deployments keep one queue PER model id — the gauge
        # series must keep them apart or one model's idle queue overwrites
        # another's backlog in the saturation signal
        self._tags = {"fn": self.name, "model": model_id}
        self.last_flush_size = 0
        self._lock = threading.Lock()
        self._queue: list[tuple[Any, Future]] = []
        self._flusher_active = False

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def submit(self, item: Any) -> Future:
        fut: Future = Future()
        flush_now = False
        with self._lock:
            self._queue.append((item, fut))
            depth = len(self._queue)
            if not self._flusher_active:
                self._flusher_active = True
                flush_now = True
        _metrics()["depth"].set(depth, tags=self._tags)
        if flush_now:
            threading.Thread(target=self._flush_loop, daemon=True).start()
        return fut

    def _flush_loop(self):
        while True:
            deadline = time.time() + self.timeout
            while time.time() < deadline:
                with self._lock:
                    if len(self._queue) >= self.max_batch_size:
                        break
                time.sleep(min(0.001, self.timeout / 10 or 0.001))
            with self._lock:
                batch = self._queue[: self.max_batch_size]
                self._queue = self._queue[self.max_batch_size :]
                if not batch:
                    self._flusher_active = False
                    _metrics()["depth"].set(0, tags=self._tags)
                    return
                depth = len(self._queue)
            self.last_flush_size = len(batch)
            _metrics()["flush"].set(len(batch), tags=self._tags)
            _metrics()["depth"].set(depth, tags=self._tags)
            items = [b[0] for b in batch]
            try:
                results = self.fn(items)
                if len(results) != len(items):
                    raise ValueError(
                        f"batched function returned {len(results)} results for "
                        f"{len(items)} inputs"
                    )
                for (_, fut), res in zip(batch, results):
                    fut.set_result(res)
            except Exception as e:
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)


def batch(
    _fn: Optional[Callable] = None,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.01,
):
    """Decorate a method taking a LIST of requests (returning a list of
    results) so concurrent single-request callers are transparently batched.

    Usage::

        class Model:
            @serve.batch(max_batch_size=32, batch_wait_timeout_s=0.005)
            def predict(self, inputs: list) -> list: ...

        # callers invoke predict(single_input) and get a single result
    """

    def wrap(fn):
        # Queues hang off the INSTANCE (created lazily at first call) so the
        # decorated class stays cloudpickle-able — a closure-held lock or
        # queue dict would break shipping the deployment to replica actors.
        # One queue PER multiplexed model id: batches never mix models, and
        # the flusher thread re-enters the submitting request's model
        # context (threading.local does not cross into the flusher).
        attr = f"__serve_batch_queues_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(self, item):
            import ray_tpu.serve.batching as _b
            from ray_tpu.serve.multiplex import (
                _set_request_model_id,
                get_multiplexed_model_id,
            )

            model_id = get_multiplexed_model_id()
            queues = getattr(self, attr, None)
            if queues is None:
                with _b._CREATE_LOCK:
                    queues = getattr(self, attr, None)
                    if queues is None:
                        queues = {}
                        setattr(self, attr, queues)
            q = queues.get(model_id)
            if q is None:
                with _b._CREATE_LOCK:
                    q = queues.get(model_id)
                    if q is None:

                        def run(items, _mid=model_id):
                            _set_request_model_id(_mid)
                            try:
                                return fn(self, items)
                            finally:
                                _set_request_model_id(None)

                        q = _BatchQueue(
                            run, max_batch_size, batch_wait_timeout_s,
                            name=fn.__name__, model_id=model_id or "",
                        )
                        queues[model_id] = q
            return q.submit(item).result()

        wrapper._is_serve_batch = True  # noqa: SLF001
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
