"""Model multiplexing: many models behind one deployment.

Reference: ``python/ray/serve/multiplex.py`` (``@serve.multiplexed`` LRU
model loader + ``serve.get_multiplexed_model_id()``) with model-aware
routing. TPU-first framing: a replica is a process holding jitted models in
HBM; multiplexing keeps up to ``max_num_models_per_replica`` loaded per
replica and routes every request for a model id to the SAME replica
(rendezvous hashing over the live replica set), so each model's weights are
resident on exactly one replica's device and swaps only happen when the
replica set changes.

    @serve.deployment
    class Multi:
        @serve.multiplexed(max_num_models_per_replica=4)
        def get_model(self, model_id: str):
            return load_jitted_model(model_id)   # heavyweight, LRU-cached

        def __call__(self, payload):
            model = self.get_model(serve.get_multiplexed_model_id())
            return model(payload)

    handle.options(multiplexed_model_id="m7").remote(x)
"""

from __future__ import annotations

import collections
import functools
import threading
from typing import Callable, Optional

_request_ctx = threading.local()


def get_multiplexed_model_id() -> str:
    """The model id the CURRENT request was routed with ('' if none)."""
    return getattr(_request_ctx, "model_id", "")


def _set_request_model_id(model_id: Optional[str]):
    _request_ctx.model_id = model_id or ""


_CREATE_LOCK = threading.Lock()


class _LRUModels:
    def __init__(self, loader: Callable, capacity: int):
        self.loader = loader
        self.capacity = capacity
        self._models: "collections.OrderedDict" = collections.OrderedDict()
        self._inflight: dict = {}  # model_id -> Future (load dedup)
        self._lock = threading.Lock()

    def get(self, instance, model_id: str):
        from concurrent.futures import Future

        while True:
            with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
                fut = self._inflight.get(model_id)
                if fut is None:
                    fut = self._inflight[model_id] = Future()
                    leader = True
                else:
                    leader = False
            if not leader:
                # another request is loading this model — share ONE load
                # (N concurrent cold requests must not jit N copies)
                return fut.result()
            try:
                model = self.loader(instance, model_id)  # load outside lock
            except BaseException as e:  # noqa: BLE001
                with self._lock:
                    self._inflight.pop(model_id, None)
                fut.set_exception(e)
                raise
            with self._lock:
                self._models[model_id] = model
                self._models.move_to_end(model_id)
                while len(self._models) > self.capacity:
                    self._models.popitem(last=False)  # LRU; GC frees it
                self._inflight.pop(model_id, None)
            fut.set_result(model)
            return model


def multiplexed(_fn: Optional[Callable] = None, *, max_num_models_per_replica: int = 3):
    """Decorate a model-loader method; concurrent calls share an LRU cache
    of at most ``max_num_models_per_replica`` loaded models per replica."""

    def wrap(fn):
        attr = f"__serve_multiplex_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(self, model_id: str):
            # runtime import: referencing module globals (the LOCK) by name
            # would make cloudpickle serialize them with user classes
            import ray_tpu.serve.multiplex as _m

            cache = getattr(self, attr, None)
            if cache is None:
                with _m._CREATE_LOCK:  # double-checked: one cache per instance
                    cache = getattr(self, attr, None)
                    if cache is None:
                        cache = _m._LRUModels(fn, max_num_models_per_replica)
                        setattr(self, attr, cache)
            return cache.get(self, model_id)

        wrapper._is_serve_multiplexed = True  # noqa: SLF001
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap


def rendezvous_pick(model_id: str, n: int) -> int:
    """Stable replica index for a model id over n replicas (highest-random-
    weight hashing): the same model keeps hitting the same replica while the
    replica set is unchanged, so its weights stay resident."""
    import hashlib

    best, best_idx = -1, 0
    for i in range(n):
        h = int.from_bytes(
            hashlib.sha1(f"{model_id}:{i}".encode()).digest()[:8], "little"
        )
        if h > best:
            best, best_idx = h, i
    return best_idx
