"""gRPC ingress for Serve applications.

Reference: ``python/ray/serve/_private/proxy.py:542`` (``gRPCProxy``) — the
reference mounts user-supplied grpc servicer functions and routes by the
``application`` request metadata. Same routing contract here, behind a
GENERIC service so no proto compilation is required on either side:

* service: ``ray.serve.GenericService``
* methods: ``Predict`` (unary-unary), ``PredictStream`` (unary-stream)
* request/response payloads: raw bytes. If the request bytes are a pickle,
  they are unpickled before reaching the deployment and the response is
  pickled back; otherwise bytes pass through untouched (interop with
  non-Python clients).
* routing: ``application`` metadata key names the target app (its ingress
  deployment, per the controller's record).

A typed client stub can still be used against this surface by registering
its serialized request bytes — the reference's typed-proto mode is a
documented departure (COVERAGE.md): it needs user proto descriptors
shipped to the proxy, which the lite design trades for zero codegen.
"""

from __future__ import annotations

import pickle
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

SERVICE = "ray.serve.GenericService"


def _maybe_unpickle(data: bytes):
    try:
        return pickle.loads(data)
    except Exception:  # noqa: BLE001 - raw-bytes clients are legitimate
        return data


def _pack(value) -> bytes:
    if isinstance(value, bytes):
        return value
    return pickle.dumps(value)


class GrpcProxyActor:
    """gRPC server routing GenericService calls to deployment handles
    (actor: lives in its own worker process, like the HTTP ProxyActor)."""

    def __init__(self, port: int = 0):
        import grpc

        self._handles: dict[str, tuple] = {}
        self._pool = ThreadPoolExecutor(max_workers=32, thread_name_prefix="grpc-proxy")
        self._server = grpc.server(self._pool, options=[("grpc.so_reuseport", 0)])
        self._server.add_generic_rpc_handlers((self._make_handler(),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")
        if self.port == 0:
            raise RuntimeError(f"gRPC proxy could not bind port {port}")
        self._server.start()

    # -- routing ------------------------------------------------------------

    def _handle_for(self, app: str):
        import ray_tpu
        from ray_tpu.serve._private.common import CONTROLLER_NAME
        from ray_tpu.serve.handle import DeploymentHandle

        ent = self._handles.get(app)
        if ent is None:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            info = ray_tpu.get(controller.get_ingress_info.remote(app), timeout=30)
            if info is None:
                raise KeyError(f"no serve application {app!r}")
            ent = (DeploymentHandle(info["deployment"]), bool(info["streaming"]))
            self._handles[app] = ent
        return ent

    def _app_of(self, context) -> str:
        md = dict(context.invocation_metadata())
        app = md.get("application")
        if not app:
            import grpc

            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "missing 'application' request metadata",
            )
        return app

    # -- grpc plumbing -------------------------------------------------------

    def _make_handler(self):
        import grpc

        actor = self

        # NB: context.abort() raises to unwind — it must NOT sit inside a
        # broad except, or every abort gets re-reported as INTERNAL

        def _resolve(context):
            app = actor._app_of(context)  # aborts INVALID_ARGUMENT itself
            try:
                return actor._handle_for(app)
            except KeyError as e:
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))

        def predict(request: bytes, context) -> bytes:
            handle, streaming = _resolve(context)
            if streaming:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "streaming app: call PredictStream",
                )
            try:
                result = handle.remote(_maybe_unpickle(request)).result(timeout=120)
            except Exception as e:  # noqa: BLE001 - deployment errors -> status
                context.abort(grpc.StatusCode.INTERNAL, repr(e))
            return _pack(result)

        def predict_stream(request: bytes, context):
            handle, streaming = _resolve(context)
            payload = _maybe_unpickle(request)
            try:
                if streaming:
                    for item in handle.options(stream=True).remote(payload):
                        yield _pack(item)
                else:  # unary app: stream of one
                    yield _pack(handle.remote(payload).result(timeout=120))
            except Exception as e:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL, repr(e))

        handlers = {
            "Predict": grpc.unary_unary_rpc_method_handler(predict),
            "PredictStream": grpc.unary_stream_rpc_method_handler(predict_stream),
        }

        class _Generic(grpc.GenericRpcHandler):
            def service(self, call_details):
                _, _, method = call_details.method.rpartition("/")
                svc = call_details.method.rsplit("/", 2)[-2] if call_details.method.count("/") >= 2 else ""
                if svc != SERVICE:
                    return None
                return handlers.get(method)

        return _Generic()

    def get_port(self) -> int:
        return self.port

    def ready(self) -> bool:
        return True

    def shutdown(self) -> bool:
        self._server.stop(grace=1.0).wait(timeout=5)
        return True


def grpc_channel_call(
    address: str, app: str, payload, timeout_s: float = 30.0, stream: bool = False
):
    """Client-side convenience (tests + python callers without stubs):
    one Predict/PredictStream call against a running gRPC proxy."""
    import grpc

    with grpc.insecure_channel(address) as channel:
        md = (("application", app),)
        if stream:
            fn = channel.unary_stream(
                f"/{SERVICE}/PredictStream",
                request_serializer=None,
                response_deserializer=None,
            )
            return [_maybe_unpickle(b) for b in fn(_pack(payload), metadata=md, timeout=timeout_s)]
        fn = channel.unary_unary(
            f"/{SERVICE}/Predict",
            request_serializer=None,
            response_deserializer=None,
        )
        return _maybe_unpickle(fn(_pack(payload), metadata=md, timeout=timeout_s))
