"""gRPC ingress for Serve applications.

Reference: ``python/ray/serve/_private/proxy.py:542`` (``gRPCProxy``) — the
reference mounts user-supplied grpc servicer functions and routes by the
``application`` request metadata. Same routing contract here, behind a
GENERIC service so no proto compilation is required on either side:

* service: ``ray.serve.GenericService``
* methods: ``Predict`` (unary-unary), ``PredictStream`` (unary-stream)
* request/response payloads: raw bytes by default — they reach the
  deployment VERBATIM and the response must be bytes/str. Deserialization
  is a per-deployment opt-in (``@serve.deployment(grpc_codec="pickle")``
  for trusted intra-cluster Python clients, or ``"json"``): running
  ``pickle.loads`` on whatever an untrusted client sends is an RCE
  surface, so the proxy never probes payloads (the reference routes typed
  protos only, ``serve/_private/proxy.py:542`` — same trust posture).
* routing: ``application`` metadata key names the target app (its ingress
  deployment, per the controller's record).

A typed client stub can still be used against this surface by registering
its serialized request bytes — the reference's typed-proto mode is a
documented departure (COVERAGE.md): it needs user proto descriptors
shipped to the proxy, which the lite design trades for zero codegen.
"""

from __future__ import annotations

import json
import pickle
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

SERVICE = "ray.serve.GenericService"
CODECS = ("bytes", "pickle", "json")


def _decode(data: bytes, codec: str, context):
    """Request bytes -> deployment argument, per the app's declared codec.
    Malformed opt-in payloads are the CLIENT's error (INVALID_ARGUMENT),
    never silently passed through."""
    import grpc

    if codec == "pickle":
        try:
            return pickle.loads(data)
        except Exception:  # noqa: BLE001
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "request is not a valid pickle"
            )
    if codec == "json":
        try:
            return json.loads(data.decode("utf-8"))
        except Exception:  # noqa: BLE001
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "request is not valid JSON"
            )
    return data  # bytes: verbatim


def _encode(value, codec: str, context) -> bytes:
    import grpc

    if codec == "pickle":
        return pickle.dumps(value)
    if codec == "json":
        try:
            return json.dumps(value).encode("utf-8")
        except (TypeError, ValueError) as e:
            context.abort(
                grpc.StatusCode.INTERNAL, f"response not JSON-serializable: {e}"
            )
    if isinstance(value, (bytes, bytearray, memoryview)):
        return bytes(value)
    if isinstance(value, str):
        return value.encode("utf-8")
    context.abort(
        grpc.StatusCode.INTERNAL,
        f"bytes-codec deployment returned {type(value).__name__}; return "
        f"bytes/str or declare grpc_codec='pickle'/'json' on the deployment",
    )


class GrpcProxyActor:
    """gRPC server routing GenericService calls to deployment handles
    (actor: lives in its own worker process, like the HTTP ProxyActor)."""

    def __init__(self, port: int = 0):
        import grpc

        self._handles: dict[str, tuple] = {}
        self._pool = ThreadPoolExecutor(max_workers=32, thread_name_prefix="grpc-proxy")
        self._server = grpc.server(self._pool, options=[("grpc.so_reuseport", 0)])
        self._server.add_generic_rpc_handlers((self._make_handler(),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")
        if self.port == 0:
            raise RuntimeError(f"gRPC proxy could not bind port {port}")
        self._server.start()

    # -- routing ------------------------------------------------------------

    def _handle_for(self, app: str):
        import ray_tpu
        from ray_tpu.serve._private.common import CONTROLLER_NAME
        from ray_tpu.serve.handle import DeploymentHandle

        import time

        ttl = 10.0
        ent = self._handles.get(app)
        now = time.monotonic()
        if ent is not None and now < ent[3]:
            return ent[:3]
        # TTL refresh: a redeploy can CHANGE the codec/streaming contract —
        # a forever-cache would keep unpickling after an operator hardened
        # the app to bytes (the exact hole this codec design closes). One
        # control RPC per app per window is noise.
        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            info = ray_tpu.get(
                controller.get_ingress_info.remote(app), timeout=10
            )
        except Exception:
            if ent is not None:
                # controller restarting: serve the STALE contract rather
                # than failing healthy replicas (re-check next window)
                self._handles[app] = (*ent[:3], now + ttl, ent[4])
                return ent[:3]
            raise
        if info is None:
            self._handles.pop(app, None)
            raise KeyError(f"no serve application {app!r}")
        if ent is not None and ent[4] == info["deployment"]:
            handle = ent[0]  # same target: keep the warm handle/router
        else:
            handle = DeploymentHandle(info["deployment"])
        ent = (
            handle,
            bool(info["streaming"]),
            info.get("codec", "bytes"),
            now + ttl,
            info["deployment"],
        )
        self._handles[app] = ent
        return ent[:3]

    def _app_of(self, context) -> str:
        md = dict(context.invocation_metadata())
        app = md.get("application")
        if not app:
            import grpc

            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "missing 'application' request metadata",
            )
        return app

    # -- grpc plumbing -------------------------------------------------------

    def _make_handler(self):
        import grpc

        actor = self

        # NB: context.abort() raises to unwind — it must NOT sit inside a
        # broad except, or every abort gets re-reported as INTERNAL

        def _resolve(context):
            app = actor._app_of(context)  # aborts INVALID_ARGUMENT itself
            try:
                return actor._handle_for(app)
            except KeyError as e:
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))

        def predict(request: bytes, context) -> bytes:
            handle, streaming, codec = _resolve(context)
            if streaming:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "streaming app: call PredictStream",
                )
            payload = _decode(request, codec, context)
            try:
                result = handle.remote(payload).result(timeout=120)
            except Exception as e:  # noqa: BLE001 - deployment errors -> status
                context.abort(grpc.StatusCode.INTERNAL, repr(e))
            return _encode(result, codec, context)

        def predict_stream(request: bytes, context):
            handle, streaming, codec = _resolve(context)
            payload = _decode(request, codec, context)

            def items():
                if streaming:
                    yield from handle.options(stream=True).remote(payload)
                else:  # unary app: stream of one
                    yield handle.remote(payload).result(timeout=120)

            it = items()
            while True:
                try:
                    item = next(it)
                except StopIteration:
                    return
                except Exception as e:  # noqa: BLE001 - deployment errors
                    context.abort(grpc.StatusCode.INTERNAL, repr(e))
                # encode OUTSIDE the except: its aborts must not be
                # re-reported as INTERNAL deployment failures
                yield _encode(item, codec, context)

        handlers = {
            "Predict": grpc.unary_unary_rpc_method_handler(predict),
            "PredictStream": grpc.unary_stream_rpc_method_handler(predict_stream),
        }

        class _Generic(grpc.GenericRpcHandler):
            def service(self, call_details):
                _, _, method = call_details.method.rpartition("/")
                svc = call_details.method.rsplit("/", 2)[-2] if call_details.method.count("/") >= 2 else ""
                if svc != SERVICE:
                    return None
                return handlers.get(method)

        return _Generic()

    def get_port(self) -> int:
        return self.port

    def ready(self) -> bool:
        return True

    def shutdown(self) -> bool:
        self._server.stop(grace=1.0).wait(timeout=5)
        return True


def _client_pack(payload, codec: str) -> bytes:
    if codec == "pickle":
        return pickle.dumps(payload)
    if codec == "json":
        return json.dumps(payload).encode("utf-8")
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return bytes(payload)
    if isinstance(payload, str):
        return payload.encode("utf-8")
    raise TypeError(
        f"bytes codec needs bytes/str payload, got {type(payload).__name__}"
    )


def _client_unpack(data: bytes, codec: str):
    if codec == "pickle":
        return pickle.loads(data)
    if codec == "json":
        return json.loads(data.decode("utf-8"))
    return data


def grpc_channel_call(
    address: str,
    app: str,
    payload,
    timeout_s: float = 30.0,
    stream: bool = False,
    codec: str = "bytes",
):
    """Client-side convenience (tests + python callers without stubs): one
    Predict/PredictStream call against a running gRPC proxy. ``codec`` must
    match the target deployment's ``grpc_codec`` declaration."""
    import grpc

    with grpc.insecure_channel(address) as channel:
        md = (("application", app),)
        data = _client_pack(payload, codec)
        if stream:
            fn = channel.unary_stream(
                f"/{SERVICE}/PredictStream",
                request_serializer=None,
                response_deserializer=None,
            )
            return [
                _client_unpack(b, codec)
                for b in fn(data, metadata=md, timeout=timeout_s)
            ]
        fn = channel.unary_unary(
            f"/{SERVICE}/Predict",
            request_serializer=None,
            response_deserializer=None,
        )
        return _client_unpack(fn(data, metadata=md, timeout=timeout_s), codec)
