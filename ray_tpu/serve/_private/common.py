"""Serve config/data types.

Reference: ``python/ray/serve/config.py`` (DeploymentConfig pydantic schemas)
and ``serve/_private/common.py`` (DeploymentID, ReplicaState). Plain
dataclasses here — configs travel through actor boundaries constantly, so
they stay cheap to pickle.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"


@dataclasses.dataclass
class AutoscalingConfig:
    """Reference: ``serve/config.py`` AutoscalingConfig /
    ``_private/autoscaling_policy.py`` (decisions from ongoing-request
    telemetry vs a per-replica target).

    Beyond ongoing counts, the controller folds in replica-exported
    ``autoscaling_metrics`` (see ``serve.llm.LLMDeployment``): queued
    requests (``queue_depth``) count toward load the same as ongoing
    ones, and any replica whose KV-cache utilization reaches
    ``kv_utilization_threshold`` adds upscale pressure even when request
    counts look calm (a memory-bound engine preempts long before its
    request count saturates)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    kv_utilization_threshold: float = 0.9


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    user_config: Optional[Any] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 1.0
    #: downscale grace: a victim replica leaves the routing set immediately
    #: but is only killed once its in-flight requests finish (or this
    #: deadline passes) — reference: graceful_shutdown_timeout_s
    graceful_shutdown_timeout_s: float = 10.0
    ray_actor_options: dict = dataclasses.field(default_factory=dict)
    #: gRPC ingress payload contract for this deployment: "bytes" (default —
    #: request/response bytes pass through VERBATIM; unpickling untrusted
    #: ingress bytes is an RCE surface, so deserialization is opt-in),
    #: "pickle" (trusted intra-cluster Python clients), or "json".
    #: Reference: the reference proxy routes typed protos only
    #: (serve/_private/proxy.py:542); this is the no-codegen analog.
    grpc_codec: str = "bytes"
    #: mid-stream failover contract (RESILIENCE.md): the name of a keyword
    #: argument the deployment's streaming methods accept that carries the
    #: items a previous replica already produced. When set, a streaming
    #: call whose replica dies is re-submitted to a fresh replica with
    #: ``<stream_resume_arg>=[items delivered so far]`` and the stream
    #: RESUMES in place instead of erroring — the deployment must continue
    #: from (not re-emit) the resumed prefix. None = streams fail over by
    #: erroring (callers retry whole requests).
    stream_resume_arg: Optional[str] = None
    #: companion to ``stream_resume_arg``: the name of a RELATIVE-seconds
    #: deadline kwarg. On failover the handle re-submits with this kwarg
    #: REDUCED by the time already spent, so the client's declared wait
    #: budget spans the whole request, not each attempt (a deadline that
    #: reset on every replica death would let failovers extend it
    #: indefinitely).
    stream_deadline_arg: Optional[str] = None


@dataclasses.dataclass
class DeploymentSpec:
    """What the controller needs to materialize one deployment."""

    name: str
    app_name: str
    callable_factory: Any      # cloudpickled zero-arg factory -> user callable
    init_args: tuple = ()
    init_kwargs: dict = dataclasses.field(default_factory=dict)
    config: DeploymentConfig = dataclasses.field(default_factory=DeploymentConfig)
    is_ingress: bool = False
    #: the user callable (or its __call__) is a generator function: HTTP
    #: responses stream chunk-by-chunk over the streaming-generator return
    #: path (reference: serve StreamingResponse over ASGI)
    streaming: bool = False


@dataclasses.dataclass
class ReplicaInfo:
    replica_id: str
    actor: Any                 # ray_tpu actor handle
    healthy: bool = True
    #: False until the replica answers its first check_health — i.e. its
    #: __init__ (model load, jit warmup) finished. Uninitialized replicas
    #: are not routed to, not counted by ready(), and not health-checked
    #: with the steady-state 5s timeout (a heavy model's init is MINUTES;
    #: judging it against the ping timeout restart-looped every slow-init
    #: replica).
    initialized: bool = False
    started_at: float = 0.0
    init_ref: Any = None       # in-flight first check_health call
