"""Replica: the actor that hosts one copy of a deployment's user callable.

Reference: ``serve/_private/replica.py:233`` (ReplicaActor wraps the user
callable via UserCallableWrapper, tracks ongoing requests, exposes
reconfigure/health hooks). TPU-first notes: a replica is the natural unit
that owns a jitted model — concurrent requests enter on the actor's thread
pool (``max_concurrency = max_ongoing_requests``) and meet the model through
``@serve.batch`` so the MXU sees one large batched call instead of N
singles.
"""

from __future__ import annotations

import threading
import time
from typing import Any


class Replica:
    """Actor body. Spawned by the controller with
    ``max_concurrency=max_ongoing_requests`` so requests execute in parallel
    threads up to the configured limit."""

    def __init__(self, replica_id: str, callable_cls, init_args, init_kwargs, user_config=None):
        self.replica_id = replica_id
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        # always a class — function deployments are wrapped by the api layer
        self._callable = callable_cls(*init_args, **init_kwargs)
        if user_config is not None:
            self.reconfigure(user_config)

    # -- request path ------------------------------------------------------

    def handle_request(self, method: str, args: tuple, kwargs: dict, model_id=None) -> Any:
        from ray_tpu._private import events as _events
        from ray_tpu.serve.multiplex import _set_request_model_id
        from ray_tpu.util import tracing as _tracing

        with self._lock:
            self._ongoing += 1
            self._total += 1
        _set_request_model_id(model_id)
        # worker_main installed the proxy/submitter trace context on this
        # thread; the span + events below correlate under that request_id
        rid = _tracing.current_request_id()
        _events.record(
            "replica.request", request_id=rid,
            replica=self.replica_id, method=method,
        )
        try:
            with _tracing.span("replica_handle", replica=self.replica_id, method=method):
                target = self._callable if method == "__call__" else getattr(self._callable, method)
                if method == "__call__" and not callable(target):
                    raise TypeError(f"Deployment {type(self._callable).__name__} is not callable")
                return target(*args, **kwargs)
        finally:
            _events.record(
                "replica.done", request_id=rid, replica=self.replica_id
            )
            _set_request_model_id(None)
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(self, method: str, args: tuple, kwargs: dict, model_id=None):
        """Generator variant: invoked with ``num_returns="streaming"`` so
        every yielded item becomes its own object as it is produced
        (reference: serve streaming responses over generator returns).
        Ongoing-count spans the WHOLE stream (admission control sees a
        streaming request as occupying its slot until exhausted)."""
        from ray_tpu._private import events as _events
        from ray_tpu.serve.multiplex import _set_request_model_id
        from ray_tpu.util import tracing as _tracing

        with self._lock:
            self._ongoing += 1
            self._total += 1
        _set_request_model_id(model_id)
        rid = _tracing.current_request_id()
        _events.record(
            "replica.request", request_id=rid,
            replica=self.replica_id, method=method, streaming=True,
        )
        try:
            target = self._callable if method == "__call__" else getattr(self._callable, method)
            out = target(*args, **kwargs)
            import inspect

            if inspect.isasyncgen(out):
                # async-generator deployments stream too: drive the agen on
                # a private loop, yielding each item into the sync stream
                import asyncio

                loop = asyncio.new_event_loop()
                try:
                    while True:
                        try:
                            yield loop.run_until_complete(out.__anext__())
                        except StopAsyncIteration:
                            break
                finally:
                    loop.close()
            else:
                yield from out
        finally:
            _events.record(
                "replica.done", request_id=rid,
                replica=self.replica_id, streaming=True,
            )
            _set_request_model_id(None)
            with self._lock:
                self._ongoing -= 1

    # -- control plane -----------------------------------------------------

    def reconfigure(self, user_config) -> bool:
        """Reference: replicas forward user_config updates to the user
        class's ``reconfigure`` method without a restart."""
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True

    def get_metrics(self) -> dict:
        m = {
            "replica_id": self.replica_id,
            "num_ongoing_requests": self._ongoing,
            "num_total_requests": self._total,
            "timestamp": time.time(),
        }
        # deployment-exported saturation signals (e.g. serve.llm's queue
        # depth / KV utilization): a continuous-batching replica absorbs
        # many requests per slot set, so ongoing counts alone under-report
        # load — the controller folds these into its scaling decision
        fn = getattr(self._callable, "autoscaling_metrics", None)
        if fn is not None:
            try:
                custom = fn()
                if isinstance(custom, dict):
                    m["autoscaling_metrics"] = custom
            except Exception:  # raylint: disable=RL007
                pass  # a broken exporter must not break health/metrics RPCs
        return m

    def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()
        return True
