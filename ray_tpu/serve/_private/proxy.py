"""HTTP ingress proxy.

Reference: ``serve/_private/proxy.py:1115`` (ProxyActor per node wrapping an
HTTP server that resolves routes to app ingress deployments and awaits the
handle response). stdlib ``ThreadingHTTPServer`` here — one thread per
in-flight request, each blocking on its DeploymentResponse; JSON in/out.

Routes: ``POST/GET /<app_name>`` → the app's ingress deployment. Body (JSON)
becomes the request payload: the ingress callable is invoked as
``__call__(payload)``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ray_tpu.serve._private.common import CONTROLLER_NAME


class ProxyActor:
    def __init__(self, port: int):
        self.port = port
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _dispatch(self):
                try:
                    app = self.path.strip("/").split("/")[0] or "default"
                    length = int(self.headers.get("Content-Length") or 0)
                    payload = json.loads(self.rfile.read(length)) if length else None
                    result = proxy._route(app, payload)
                    body = json.dumps(result).encode()
                    self.send_response(200)
                except KeyError as e:
                    body = json.dumps({"error": str(e)}).encode()
                    self.send_response(404)
                except Exception as e:  # noqa: BLE001
                    body = json.dumps({"error": repr(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            do_GET = _dispatch
            do_POST = _dispatch

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            request_queue_size = 256  # default 5 resets bursty clients

        self._server = _Server(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]  # resolves port=0
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        self._handles: dict[str, object] = {}

    def _route(self, app: str, payload):
        import ray_tpu
        from ray_tpu.serve.handle import DeploymentHandle

        handle = self._handles.get(app)
        if handle is None:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            ingress = ray_tpu.get(controller.get_ingress.remote(app), timeout=30)
            if ingress is None:
                raise KeyError(f"no app {app!r}")
            handle = DeploymentHandle(ingress)
            self._handles[app] = handle
        return handle.remote(payload).result(timeout=60)

    def ready(self) -> int:
        return self.port

    def get_port(self) -> int:
        return self.port

    def stop(self) -> bool:
        self._server.shutdown()
        return True

    def check_health(self) -> bool:
        return True
