"""HTTP ingress proxy.

Reference: ``serve/_private/proxy.py:1115`` (ProxyActor per node wrapping an
HTTP server that resolves routes to app ingress deployments and awaits the
handle response; ``proxy.py:759`` streams ASGI responses). stdlib
``ThreadingHTTPServer`` here — one thread per in-flight request, each
blocking on its DeploymentResponse. The controller runs one ProxyActor per
alive node; any proxy routes to any replica.

Routes: ``POST/GET /<app_name>`` → the app's ingress deployment, invoked as
``__call__(payload)``. Bodies: JSON stays JSON, ``text/*`` arrives as str,
anything else as raw bytes; responses mirror (bytes → octet-stream, str →
text/plain, else JSON). Generator ingress deployments stream chunked
(one chunk per yielded item, via ``num_returns="streaming"``).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ray_tpu.serve._private.common import CONTROLLER_NAME


class ProxyActor:
    def __init__(self, port: int):
        self.port = port
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"  # chunked responses need 1.1

            def log_message(self, *args):  # quiet
                pass

            def _read_payload(self):
                """JSON stays JSON; anything else arrives as raw bytes
                (reference: the ASGI proxy hands the body through; JSON is a
                convenience, not a requirement)."""
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b""
                ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
                if not raw:
                    return None
                if ctype in ("", "application/json"):
                    return json.loads(raw)
                if ctype.startswith("text/"):
                    return raw.decode()
                return raw

            def _send_body(self, code: int, body, ctype=None):
                if isinstance(body, (bytes, bytearray, memoryview)):
                    data = bytes(body)
                    ctype = ctype or "application/octet-stream"
                elif isinstance(body, str):
                    data = body.encode()
                    ctype = ctype or "text/plain; charset=utf-8"
                else:
                    data = json.dumps(body).encode()
                    ctype = ctype or "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _send_stream(self, items):
                """Chunked transfer: one chunk per generator item as it is
                produced (bytes raw; anything else NDJSON). Errors after the
                200 header cannot become a second response — log and drop
                the connection so the client sees a clean truncation."""
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(data: bytes):
                    self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
                    self.wfile.flush()

                try:
                    for item in items:
                        if isinstance(item, (bytes, bytearray, memoryview)):
                            chunk(bytes(item))
                        else:
                            chunk((json.dumps(item) + "\n").encode())
                    self.wfile.write(b"0\r\n\r\n")
                except BaseException:  # noqa: BLE001
                    # swallow: a second HTTP response injected into an open
                    # chunked stream would corrupt the framing — log and
                    # drop the connection (clean truncation for the client)
                    import traceback

                    print("[serve-proxy] streaming response failed:", flush=True)
                    traceback.print_exc()
                    self.close_connection = True

            def _dispatch(self):
                try:
                    app = self.path.strip("/").split("/")[0] or "default"
                    payload = self._read_payload()
                    handle, streaming = proxy._handle_for(app)
                    if streaming:
                        resp = handle.options(stream=True).remote(payload)
                        self._send_stream(resp)
                        return
                    result = handle.remote(payload).result(timeout=60)
                    self._send_body(200, result)
                except KeyError as e:
                    self._send_body(404, {"error": str(e)})
                except Exception as e:  # noqa: BLE001
                    self._send_body(500, {"error": repr(e)})

            do_GET = _dispatch
            do_POST = _dispatch

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            request_queue_size = 256  # default 5 resets bursty clients

        self._server = _Server(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]  # resolves port=0
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        self._handles: dict[str, object] = {}

    def _handle_for(self, app: str):
        import ray_tpu
        from ray_tpu.serve.handle import DeploymentHandle

        ent = self._handles.get(app)
        if ent is None:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            info = ray_tpu.get(controller.get_ingress_info.remote(app), timeout=30)
            if info is None:
                raise KeyError(f"no app {app!r}")
            ent = (DeploymentHandle(info["deployment"]), bool(info["streaming"]))
            self._handles[app] = ent
        return ent

    def ready(self) -> int:
        return self.port

    def get_port(self) -> int:
        return self.port

    def stop(self) -> bool:
        self._server.shutdown()
        return True

    def check_health(self) -> bool:
        return True
