"""HTTP ingress proxy — asyncio + h11.

Reference: ``serve/_private/proxy.py:1115`` (ProxyActor per node wrapping an
HTTP server that resolves routes to app ingress deployments and awaits the
handle response; ``proxy.py:759`` runs uvicorn/ASGI). The round-3
``ThreadingHTTPServer`` held one OS thread per in-flight request and
collapsed under concurrency; this proxy is a single asyncio event loop
(h11 for HTTP/1.1 parsing/framing — the same state machine family the
reference's uvicorn uses) with:

* a bounded dispatch executor for the blocking control-plane touches
  (first-route lookup, router admission/pick, result fetches, failover
  re-picks) — never occupied for a request's full lifetime;
* ONE resolver thread that watches ALL in-flight unary ObjectRefs via a
  single batched ``ray_tpu.wait`` — hundreds of concurrent requests cost
  hundreds of parked coroutines, not hundreds of threads;
* router semantics preserved end-to-end: the handle slot is held until the
  response settles (admission caps + pow-2 balancing stay live) and replica
  death re-routes through ``DeploymentResponse._async_failed`` exactly like
  the blocking ``result()`` path;
* streaming responses on a dedicated thread per stream with a bounded
  in-flight chunk window and client-disconnect cancellation (the generator
  is closed, which disposes the remote stream).

Routes: ``POST/GET /<app_name>`` → the app's ingress deployment, invoked as
``__call__(payload)``. Bodies: JSON stays JSON, ``text/*`` arrives as str,
anything else as raw bytes; responses mirror (bytes → octet-stream, str →
text/plain, else JSON). Generator ingress deployments stream chunked
(one chunk per yielded item, via ``num_returns="streaming"``).
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import h11

from ray_tpu._private import events as _events
from ray_tpu.serve._private.common import CONTROLLER_NAME
from ray_tpu.util import phases as _phases
from ray_tpu.util import tracing as _tracing

_READ_CHUNK = 1 << 16
_DISPATCH_THREADS = 32  # blocking picks/lookups/fetches — never held per-request
_STREAM_WINDOW = 64  # max un-consumed chunks in flight per stream
_UNARY_TIMEOUT_S = 60.0

_request_counter = None
_request_counter_lock = threading.Lock()


def _count_request(status: int) -> None:
    """Bump the ``serve_requests`` counter by status class. Feeds the
    request-errors SLO (``util.slo.default_rules``) and the request-rate
    line in ``obs top`` — the flight-recorder events alone can't, their
    ring wraps."""
    global _request_counter
    if _request_counter is None:
        with _request_counter_lock:
            if _request_counter is None:
                from ray_tpu.util.metrics import Counter

                _request_counter = Counter(
                    "serve_requests",
                    "proxied HTTP requests by status class",
                    tag_keys=("status",),
                )
    _request_counter.inc(tags={"status": f"{int(status) // 100}xx"})


class _Resolution:
    """One in-flight unary request: its asyncio future plus the CURRENT
    response being awaited (failover swaps in a re-routed response)."""

    __slots__ = ("loop", "future", "resp")

    def __init__(self, loop, resp):
        self.loop = loop
        self.future = loop.create_future()
        self.resp = resp


class _RefResolver:
    """Settles every in-flight unary request with one watcher thread.

    The thread batches all outstanding refs into a single ``ray_tpu.wait``;
    ready refs are handed to the dispatch pool to fetch + settle (a big
    payload fetch must not head-of-line-block other settlements), post the
    result to the owning event loop, and — on replica death — re-route via
    ``DeploymentResponse._async_failed`` and re-register the fresh ref.
    """

    def __init__(self):
        # OWN pool, never shared with dispatch: dispatch threads block in
        # pick() waiting for router slots that only _finish (settle) frees —
        # sharing one pool deadlocks the proxy at max_ongoing saturation
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="proxy-finish"
        )
        self._lock = threading.Lock()
        self._pending: dict = {}  # ObjectRef -> _Resolution
        self._wake = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="proxy-resolver", daemon=True
        )
        self._thread.start()

    def register(self, resp, loop) -> _Resolution:
        res = _Resolution(loop, resp)
        with self._lock:
            self._pending[resp._async_ref()] = res
        self._wake.set()
        return res

    def _rearm(self, res: _Resolution, resp) -> None:
        res.resp = resp
        with self._lock:
            self._pending[resp._async_ref()] = res
        self._wake.set()

    def discard(self, res: _Resolution) -> None:
        """Caller timed out / disconnected: stop tracking (and free the
        router slot so abandoned requests don't eat the admission cap)."""
        with self._lock:
            ref = res.resp._async_ref()
            if self._pending.get(ref) is res:
                self._pending.pop(ref, None)
        try:
            res.resp._async_done()
        except Exception:
            pass

    def close(self):
        self._closed = True
        self._wake.set()
        self._pool.shutdown(wait=False)

    def _run(self):
        import ray_tpu

        while not self._closed:
            with self._lock:
                refs = list(self._pending.keys())
            if not refs:
                self._wake.wait(timeout=1.0)
                self._wake.clear()
                continue
            try:
                ready, _ = ray_tpu.wait(
                    refs, num_returns=len(refs), timeout=0.05, fetch_local=False
                )
            except Exception:
                ready = []
            for ref in ready:
                with self._lock:
                    res = self._pending.pop(ref, None)
                if res is not None:
                    self._pool.submit(self._finish, ref, res)

    def _finish(self, ref, res: _Resolution):
        """Dispatch-pool side: fetch the value, settle the router slot, post
        to the event loop; on failure mirror result()'s failover."""
        import ray_tpu

        try:
            value = ray_tpu.get(ref)  # ready: no artificial timeout
            res.resp._async_done()
            err = None
        except BaseException as e:  # noqa: BLE001
            try:
                nxt = res.resp._async_failed(e)  # may block in pick(): pool thread
            except BaseException as pick_err:  # noqa: BLE001
                nxt = None
                e = pick_err
            if nxt is not None:
                self._rearm(res, nxt)
                return
            value, err = None, e
        def _post():
            if res.future.cancelled():
                return
            if err is not None:
                res.future.set_exception(err)
            else:
                res.future.set_result(value)
        try:
            res.loop.call_soon_threadsafe(_post)
        except RuntimeError:
            pass  # loop already closed (proxy stopping)


def _error_status(exc) -> tuple[int, list[tuple[str, str]]]:
    """HTTP status + extra headers for a request-path failure. 429 carries
    ``Retry-After`` (seconds, ceil'd — the header is integer-valued) from
    the shedding layer's estimate of when capacity frees up."""
    from ray_tpu.exceptions import OverloadedError

    import math

    cause = getattr(exc, "cause", None)
    if isinstance(exc, OverloadedError) or isinstance(cause, OverloadedError):
        # the shedding layer's estimate rides retry_after_s — on the raw
        # error directly, or on .cause when the error crossed an actor
        # boundary (RayTaskError's as_instanceof_cause carries the original
        # in .cause but not its attributes)
        retry_s = getattr(exc, "retry_after_s", None)
        if retry_s is None:
            retry_s = getattr(cause, "retry_after_s", 1.0)
        retry_after = max(1, math.ceil(retry_s))
        return 429, [("retry-after", str(retry_after))]
    if isinstance(exc, KeyError):
        return 404, []
    return 500, []


def _parse_payload(body: bytes, ctype: str):
    """JSON stays JSON; anything else arrives as raw bytes (reference: the
    ASGI proxy hands the body through; JSON is a convenience)."""
    if not body:
        return None
    ctype = (ctype or "").split(";")[0].strip()
    if ctype in ("", "application/json"):
        return json.loads(body)
    if ctype.startswith("text/"):
        return body.decode()
    return body


def _encode_body(body) -> tuple[bytes, str]:
    if isinstance(body, (bytes, bytearray, memoryview)):
        return bytes(body), "application/octet-stream"
    if isinstance(body, str):
        return body.encode(), "text/plain; charset=utf-8"
    return json.dumps(body).encode(), "application/json"


class _StreamCancelled(BaseException):
    pass


#: raylint RL017 — _handles is a per-app handle cache: dict get/store are
#: GIL-atomic, and two request threads racing the first touch at worst
#: both build a handle (idempotent — last store wins, both work)
LOCKFREE = ("ProxyActor._handles: atomic",)


class ProxyActor:
    def __init__(self, port: int):
        self.port = port
        self._handles: dict[str, object] = {}
        self._dispatch_pool = ThreadPoolExecutor(
            max_workers=_DISPATCH_THREADS, thread_name_prefix="proxy-dispatch"
        )
        self._resolver = _RefResolver()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        started = threading.Event()
        boot_err: list = []

        def run_loop():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def boot():
                try:
                    self._server = await asyncio.start_server(
                        self._handle_conn, "127.0.0.1", port, backlog=1024
                    )
                    self.port = self._server.sockets[0].getsockname()[1]
                except BaseException as e:  # noqa: BLE001
                    boot_err.append(e)
                finally:
                    started.set()

            loop.run_until_complete(boot())
            if not boot_err:
                loop.run_forever()
            # drain callbacks after stop() so close() completes cleanly
            loop.run_until_complete(asyncio.sleep(0))
            loop.close()

        self._thread = threading.Thread(target=run_loop, name="proxy-loop", daemon=True)
        self._thread.start()
        started.wait(timeout=30)
        if boot_err:
            raise boot_err[0]

    # ------------------------------------------------------------- routing

    def _handle_for(self, app: str):
        """Blocking (controller RPC) on first touch — always called from a
        worker thread, never the event loop."""
        import ray_tpu
        from ray_tpu.serve.handle import DeploymentHandle

        ent = self._handles.get(app)
        if ent is None:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            info = ray_tpu.get(controller.get_ingress_info.remote(app), timeout=30)
            if info is None:
                raise KeyError(f"no app {app!r}")
            ent = (DeploymentHandle(info["deployment"]), bool(info["streaming"]))
            self._handles[app] = ent
        return ent

    #: longest the capacity probe will wait for a slot before declaring
    #: overload, however generous the deadline — a capacity drought this
    #: long with every replica at its admission cap IS overload, and
    #: backpressuring the patient client (429 + Retry-After, they retry)
    #: beats silently parking unbounded queue depth in the router
    _SHED_PROBE_MAX_S = 2.0

    def _shed_if_doomed(self, handle, app: str, deadline_s, request_id: str):
        """Proxy-side deadline-aware admission (RESILIENCE.md): a request
        that declares a deadline (``x-deadline-s`` header) and cannot get
        an admission slot within a probe window scaled to that deadline
        (half of it, capped at ``_SHED_PROBE_MAX_S``) is rejected with
        429/Retry-After instead of parking in pick() behind work that
        outlives it. A momentary full house at steady load clears within
        the probe and admits normally — only a sustained drought sheds.
        Requests without a deadline queue as before; an unknown replica
        set (cold router) never sheds."""
        if deadline_s is None:
            return
        budget = min(max(deadline_s, 0.0) * 0.5, self._SHED_PROBE_MAX_S)
        deadline = time.monotonic() + budget
        while True:
            free = handle.free_capacity()
            if free is None or free > 0:
                return
            if time.monotonic() >= deadline:
                break
            time.sleep(0.02)
        from ray_tpu.exceptions import OverloadedError

        _events.record(
            "proxy.shed", request_id=request_id, app=app,
            deadline_s=deadline_s, probe_s=round(budget, 3),
        )
        raise OverloadedError(
            f"all {app!r} replicas held their admission caps for "
            f"{budget:.2f}s and the request carries a {deadline_s}s "
            "deadline",
            retry_after_s=1.0,
        )

    def _route(self, app: str, payload, request_id: str, deadline_s=None):
        """Dispatch pool (ONE hop per request): route lookup + admission/
        pick may block. Returns ("stream", None) for streaming apps, else
        ("unary", un-settled DeploymentResponse) — the slot stays held until
        resolution so admission caps and pow-2 balancing see async requests
        exactly like blocking callers. The request's trace context is
        installed on this dispatch thread so the replica submission (an
        actor-method hop) carries the request_id downstream."""
        with _tracing.trace_context(request_id):
            handle, streaming = self._handle_for(app)
            if streaming:
                return "stream", None
            self._shed_if_doomed(handle, app, deadline_s, request_id)
            with _tracing.span("proxy_route", app=app):
                return "unary", handle.remote(payload)

    def _run_stream(self, app: str, payload, loop, q: "asyncio.Queue",
                    cancel: threading.Event, window: threading.Semaphore,
                    request_id: str = "", deadline_s=None, stamps=None):
        """Dedicated thread per stream (long-lived by nature — must not
        occupy the dispatch pool): iterates the streaming generator with a
        bounded chunk window and stops (disposing the remote stream) when
        the client disconnects. Sentinels: ("end", None) | ("error", exc)."""

        def post(item):
            loop.call_soon_threadsafe(q.put_nowait, item)

        gen = None
        try:
            # trace context on the stream thread: the streaming replica hop
            # inherits the proxy-minted request_id (mint_context makes the
            # head-sampling decision once; an unsampled stream ships no
            # context downstream and records no spans)
            ctx = _tracing.mint_context(request_id) if request_id else None
            _tracing.set_trace_context(ctx)
            handle, _ = self._handle_for(app)
            self._shed_if_doomed(handle, app, deadline_s, request_id)
            if stamps is not None:
                # phase-ledger dispatch anchor: kept proxy-side for the
                # fold AND ridden downstream on the sampled trace-ctx dict
                # so the engine can observe the cross-process dispatch leg
                # (phases.note_dispatch)
                t_disp = time.time()
                stamps["t_dispatch"] = t_disp
                if type(ctx) is dict:
                    ctx["t_dispatch"] = t_disp
            gen = handle.options(stream=True).remote(payload)
            for item in gen:
                if isinstance(item, (bytes, bytearray, memoryview)):
                    data = bytes(item)
                else:
                    data = (json.dumps(item) + "\n").encode()
                while not window.acquire(timeout=0.25):
                    if cancel.is_set():
                        raise _StreamCancelled
                if cancel.is_set():
                    raise _StreamCancelled
                post(("chunk", data))
            if stamps is not None:
                # done-sentinel receipt ≈ engine finish + one hop; the
                # `stream` phase (delivery tail) starts here
                stamps["t_finish"] = time.time()
            post(("end", None))
        except _StreamCancelled:
            pass
        except BaseException as e:  # noqa: BLE001
            post(("error", e))
        finally:
            if gen is not None and cancel.is_set():
                try:
                    gen.close()  # disposes the remote stream + producer
                except Exception:
                    pass

    # ------------------------------------------------------- http plumbing

    async def _read_request(self, conn: h11.Connection, reader, writer):
        """Collect one (Request, body) off the connection; None on close.
        Answers ``Expect: 100-continue`` with the interim response so
        clients that wait for it (curl on >1KB bodies) don't stall."""
        request = None
        body = b""
        while True:
            event = conn.next_event()
            if event is h11.NEED_DATA:
                data = await reader.read(_READ_CHUNK)
                conn.receive_data(data)
                if data == b"" and request is None:
                    return None  # clean close between requests
                continue
            if isinstance(event, h11.Request):
                request = event
                expect = next(
                    (v for k, v in request.headers if k == b"expect"), b""
                )
                if expect.lower() == b"100-continue":
                    await self._send(
                        writer, conn, h11.InformationalResponse(status_code=100)
                    )
            elif isinstance(event, h11.Data):
                body += event.data
            elif isinstance(event, h11.EndOfMessage):
                return request, body
            elif isinstance(event, (h11.ConnectionClosed,)):
                return None

    async def _send(self, writer, conn, event):
        data = conn.send(event)
        if data:
            writer.write(data)
            await writer.drain()

    async def _respond(self, writer, conn, code: int, body, ctype=None,
                       request_id: str = "", extra_headers=()):
        data, default_ctype = _encode_body(body)
        headers = [
            ("content-type", ctype or default_ctype),
            ("content-length", str(len(data))),
            *extra_headers,
        ]
        if request_id:
            # clients correlate their response with `obs req <id>` by this
            headers.append(("x-request-id", request_id))
        await self._send(writer, conn, h11.Response(status_code=code, headers=headers))
        await self._send(writer, conn, h11.Data(data=data))
        await self._send(writer, conn, h11.EndOfMessage())

    async def _respond_stream(self, writer, conn, app: str, payload, loop,
                              request_id: str = "", deadline_s=None,
                              t_recv=None):
        """Chunked transfer: h11 frames chunks automatically when no
        content-length is declared. Errors after the header cannot become a
        second response — truncate the stream (close) like the reference."""
        q: asyncio.Queue = asyncio.Queue()
        cancel = threading.Event()
        window = threading.Semaphore(_STREAM_WINDOW)
        # phase-ledger anchors for this request (util.phases): the stream
        # thread writes dispatch/finish, this coroutine first-chunk, and
        # the successful-completion branch folds them
        stamps = {} if _phases.enabled() else None
        if t_recv is None:
            t_recv = time.time()
        threading.Thread(
            target=self._run_stream,
            args=(app, payload, loop, q, cancel, window, request_id,
                  deadline_s, stamps),
            name="proxy-stream",
            daemon=True,
        ).start()
        try:
            first_kind, first_val = await q.get()
            window.release()
            if first_kind == "error":
                code, extra = _error_status(first_val)
                _count_request(code)
                _events.record(
                    "proxy.response", request_id=request_id, status=code,
                    error=repr(first_val), streaming=True,
                )
                await self._respond(
                    writer, conn, code, {"error": repr(first_val)},
                    request_id=request_id, extra_headers=extra,
                )
                return False
            headers = [
                ("content-type", "application/octet-stream"),
                ("transfer-encoding", "chunked"),
            ]
            if request_id:
                headers.append(("x-request-id", request_id))
            await self._send(
                writer, conn, h11.Response(status_code=200, headers=headers)
            )
            kind, val = first_kind, first_val
            while True:
                if kind == "chunk":
                    if stamps is not None and "t_first" not in stamps:
                        stamps["t_first"] = time.time()
                    await self._send(writer, conn, h11.Data(data=val))
                elif kind == "end":
                    await self._send(writer, conn, h11.EndOfMessage())
                    _count_request(200)
                    if stamps is not None:
                        _phases.fold_proxy(
                            request_id, t_recv,
                            stamps.get("t_dispatch"),
                            stamps.get("t_first"),
                            stamps.get("t_finish"),
                            time.time(),
                        )
                    return True
                else:  # mid-stream error: truncate
                    import traceback

                    _count_request(500)
                    _events.record(
                        "proxy.stream_error", request_id=request_id,
                        error=repr(val),
                    )
                    print("[serve-proxy] streaming response failed:", flush=True)
                    traceback.print_exception(val)
                    writer.close()
                    return False
                kind, val = await q.get()
                window.release()
        finally:
            cancel.set()  # stops (and disposes) the producer on disconnect

    async def _handle_conn(self, reader, writer):
        loop = asyncio.get_running_loop()
        conn = h11.Connection(h11.SERVER)
        try:
            while True:
                try:
                    req = await self._read_request(conn, reader, writer)
                except h11.RemoteProtocolError:
                    await self._send(
                        writer, conn,
                        h11.Response(status_code=400, headers=[("content-length", "0")]),
                    )
                    await self._send(writer, conn, h11.EndOfMessage())
                    return
                if req is None:
                    return
                request, body = req
                target = request.target.decode()
                headers = {k.decode().lower(): v.decode() for k, v in request.headers}
                app = target.strip("/").split("/")[0] or "default"
                # trace root: honor a caller-supplied x-request-id (gateway
                # chains) or mint one; it rides the task specs downstream
                # and echoes back in the response header
                rid = headers.get("x-request-id") or _tracing.new_request_id()
                # deadline-aware shedding opt-in: a client that can't use a
                # late response declares how long it will wait. Hostile
                # values (nan/inf/negative — float() accepts them all) are
                # ignored rather than fed into probe-loop arithmetic.
                import math

                try:
                    deadline_s = float(headers["x-deadline-s"])
                    if not math.isfinite(deadline_s) or deadline_s <= 0:
                        deadline_s = None
                except (KeyError, ValueError):
                    deadline_s = None
                t_req = time.time()
                _events.record(
                    "proxy.request", request_id=rid, app=app,
                    method=request.method.decode(), bytes_in=len(body),
                )
                try:
                    payload = _parse_payload(body, headers.get("content-type", ""))
                    kind, resp = await loop.run_in_executor(
                        self._dispatch_pool, self._route, app, payload, rid,
                        deadline_s,
                    )
                    if kind == "stream":
                        ok = await self._respond_stream(
                            writer, conn, app, payload, loop, request_id=rid,
                            deadline_s=deadline_s, t_recv=t_req,
                        )
                        if ok:
                            # failures already recorded proxy.response /
                            # proxy.stream_error inside _respond_stream
                            _events.record(
                                "proxy.stream_done", request_id=rid,
                                dur_s=round(time.time() - t_req, 6),
                            )
                    else:
                        res = self._resolver.register(resp, loop)
                        try:
                            result = await asyncio.wait_for(
                                res.future, timeout=_UNARY_TIMEOUT_S
                            )
                        except (asyncio.TimeoutError, asyncio.CancelledError):
                            self._resolver.discard(res)  # free slot + tracking
                            raise
                        _count_request(200)
                        _events.record(
                            "proxy.response", request_id=rid, status=200,
                            dur_s=round(time.time() - t_req, 6),
                        )
                        await self._respond(writer, conn, 200, result, request_id=rid)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001
                    code, extra = _error_status(e)
                    _count_request(code)
                    _events.record(
                        "proxy.response", request_id=rid, status=code,
                        error=repr(e) if code != 404 else str(e),
                    )
                    try:
                        await self._respond(
                            writer, conn, code,
                            {"error": str(e) if code == 404 else repr(e)},
                            request_id=rid, extra_headers=extra,
                        )
                    except h11.LocalProtocolError:
                        return  # headers already sent (stream): just close
                # keep-alive
                if conn.our_state is h11.MUST_CLOSE or conn.their_state is h11.MUST_CLOSE:
                    return
                try:
                    conn.start_next_cycle()
                except h11.LocalProtocolError:
                    return
        except (ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    # ------------------------------------------------------------ lifecycle

    def ready(self) -> int:
        return self.port

    def get_port(self) -> int:
        return self.port

    def stop(self) -> bool:
        self._resolver.close()
        loop = self._loop
        if loop is not None and loop.is_running():
            def _shut():
                if self._server is not None:
                    self._server.close()
                loop.stop()
            loop.call_soon_threadsafe(_shut)
        self._dispatch_pool.shutdown(wait=False)
        return True

    def check_health(self) -> bool:
        return True
