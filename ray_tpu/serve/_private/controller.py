"""ServeController: the control-plane actor reconciling target deployment
state into replica actors.

Reference: ``serve/_private/controller.py:91`` (ServeController run loop),
``_private/deployment_state.py:1221`` (DeploymentState reconciliation:
target replicas vs running, starting/stopping), ``autoscaling_policy.py``
(ongoing-request-driven replica counts). One controller actor per cluster
(named actor ``SERVE_CONTROLLER``); a background reconcile thread diffs
target vs actual every ``RECONCILE_PERIOD_S``, restarts dead replicas,
applies autoscaling decisions from replica metrics, and bumps a version
counter that handle-side routers long-poll to refresh their replica sets.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from ray_tpu._private import events as _events
from ray_tpu._private.log_util import warn_throttled
from ray_tpu.serve._private.common import (
    AutoscalingConfig,
    DeploymentSpec,
    ReplicaInfo,
)

RECONCILE_PERIOD_S = 0.25
#: A replica that has not finished __init__ (answered its first health
#: check) within this window is declared failed and replaced.
REPLICA_INIT_TIMEOUT_S = 120.0


def desired_replicas(
    cfg: AutoscalingConfig, metrics: list[dict], current: int,
    alerts: tuple = (),
) -> int:
    """Pure scaling decision from one round of replica metrics.

    Load is ongoing requests PLUS replica-exported queue depth (a
    continuous-batching replica holds admitted streams in its engine
    queue, invisible to ongoing counts alone), divided by the per-replica
    target.  A replica at/above the KV-utilization threshold adds one
    replica of upscale pressure on top — a memory-bound engine preempts
    and thrashes long before its request count looks saturated.  A FIRING
    SLO alert labeled ``serve=upscale`` (the head's burn-rate engine —
    e.g. TTFT p99 burning its budget) does the same: latency degradation
    is upscale pressure even when request counts look fine.  Bounded by
    [min_replicas, max_replicas]; delay/hysteresis is the caller's
    (``_autoscale``'s) job."""
    total_load = 0.0
    kv_max = 0.0
    for m in metrics:
        total_load += m.get("num_ongoing_requests", 0)
        custom = m.get("autoscaling_metrics") or {}
        total_load += custom.get("queue_depth", 0)
        kv_max = max(kv_max, custom.get("kv_utilization", 0.0))
    desired = (
        -(-int(total_load) // max(int(cfg.target_ongoing_requests), 1))
        or cfg.min_replicas
    )
    if kv_max >= cfg.kv_utilization_threshold:
        desired = max(desired, current + 1)
    if any((a.get("labels") or {}).get("serve") == "upscale" for a in alerts):
        desired = max(desired, current + 1)
    return max(cfg.min_replicas, min(cfg.max_replicas, desired))


class _DeploymentState:
    def __init__(self, spec: DeploymentSpec):
        self.spec = spec
        self.replicas: list[ReplicaInfo] = []
        self.target_replicas = spec.config.num_replicas
        if spec.config.autoscaling_config:
            self.target_replicas = max(
                spec.config.autoscaling_config.min_replicas, 1
            )
        # downscale victims draining in-flight requests: (ReplicaInfo,
        # kill-deadline) — out of the routing set, not yet killed
        self.draining: list[tuple[ReplicaInfo, float]] = []
        # autoscaling bookkeeping
        self._scale_pressure_since: Optional[float] = None
        self._scale_direction = 0


class ServeController:
    def __init__(self):
        self._lock = threading.RLock()
        self._deployments: dict[str, _DeploymentState] = {}
        self._apps: dict[str, list[str]] = {}   # app -> deployment names
        self._ingress: dict[str, str] = {}      # app -> ingress deployment
        self._version = 0
        # long-poll push (reference: _private/long_poll.py LongPollHost):
        # routers park in poll_replicas on this condition and are woken by
        # every version bump — zero steady-state pulls
        self._version_cv = threading.Condition(self._lock)
        self.replica_pulls = 0  # get_replicas calls (tests assert no polling)
        self._proxy = None
        self._proxies: dict[str, tuple] = {}  # node_id hex -> (actor, port)
        self._proxy_req_port: Optional[int] = None
        self._grpc_proxy: Optional[tuple] = None  # (actor, port)
        # serializes _ensure_proxies: ensure_proxy (serve.run) racing the
        # reconcile thread once created TWO proxies for one node — the dict
        # overwrite dropped the first proxy's only handle, and the head
        # reaps handle-less actors, killing it mid-request
        self._proxy_mutex = threading.Lock()
        # firing-SLO-alert cache for the autoscale hook: the reconcile loop
        # runs every 0.25s and must not hammer the head's alert RPC
        self._alerts_cache: tuple[float, list] = (0.0, [])
        self._shutdown = False
        self._reconciler = threading.Thread(target=self._reconcile_loop, daemon=True)
        self._reconciler.start()

    def _bump_version_locked(self) -> None:
        self._version += 1
        self._version_cv.notify_all()

    # -- deploy API --------------------------------------------------------

    def deploy_application(self, app_name: str, specs: list[DeploymentSpec]) -> bool:
        """Set target state for an app (idempotent; re-deploy replaces)."""
        with self._lock:
            old = self._apps.get(app_name, [])
            new_names = {s.name for s in specs}
            for name in old:
                if name not in new_names:
                    self._stop_deployment(name)
            self._apps[app_name] = [s.name for s in specs]
            for spec in specs:
                existing = self._deployments.get(spec.name)
                if existing is not None:
                    existing.spec = spec
                    if spec.config.autoscaling_config is None:
                        existing.target_replicas = spec.config.num_replicas
                    for r in existing.replicas:  # push new user_config live
                        if spec.config.user_config is not None:
                            r.actor.reconfigure.remote(spec.config.user_config)
                else:
                    self._deployments[spec.name] = _DeploymentState(spec)
                if spec.is_ingress:
                    self._ingress[app_name] = spec.name
            self._bump_version_locked()
        self._reconcile_once()
        return True

    def delete_application(self, app_name: str) -> bool:
        with self._lock:
            for name in self._apps.pop(app_name, []):
                self._stop_deployment(name)
            self._ingress.pop(app_name, None)
            self._bump_version_locked()
        return True

    def _stop_deployment(self, name: str):
        state = self._deployments.pop(name, None)
        if state is None:
            return
        import ray_tpu

        # draining victims too: once the deployment is gone nothing else
        # would ever reap them (the reconcile loop only sees _deployments)
        victims = list(state.replicas) + [r for r, _ in state.draining]
        state.draining = []
        for r in victims:
            try:
                ray_tpu.kill(r.actor)
            except Exception:  # raylint: disable=RL007
                pass  # best-effort teardown: the replica may already be dead

    # -- queries (handles / proxy / status) --------------------------------

    def get_replicas(self, deployment_name: str) -> tuple[int, list, int]:
        """(version, [actor handles], max_ongoing) — routers cache and
        re-pull on change; max_ongoing is the per-replica admission cap."""
        with self._lock:
            self.replica_pulls += 1
            return self._replicas_locked(deployment_name)

    def _replicas_locked(self, deployment_name: str) -> tuple[int, list, int]:
        state = self._deployments.get(deployment_name)
        if state is None:
            return self._version, [], 1
        return (
            self._version,
            # only initialized replicas route: a request queued on a replica
            # still loading its model would wait out the whole init inside
            # the actor's task queue
            [r.actor for r in state.replicas if r.healthy and r.initialized],
            max(state.spec.config.max_ongoing_requests, 1),
        )

    def poll_replicas(
        self, deployment_name: str, known_version: int, timeout: float = 25.0
    ) -> tuple[int, list, int]:
        """Long-poll push (reference: _private/long_poll.py): parks until
        the config version moves past ``known_version`` (or the timeout
        heartbeats), then returns the fresh replica set. Routers keep one
        of these outstanding instead of polling get_replicas — requires the
        controller actor's max_concurrency to cover the router count."""
        deadline = time.time() + timeout
        with self._lock:
            while self._version == known_version and not self._shutdown:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._version_cv.wait(remaining)
            return self._replicas_locked(deployment_name)

    def get_pull_count(self) -> int:
        return self.replica_pulls

    def get_stream_resume_arg(self, deployment_name: str) -> Optional[tuple]:
        """The deployment's mid-stream-failover contract —
        ``(stream_resume_arg, stream_deadline_arg)`` — or None when streams
        are not resumable. Routers cache this once per handle; it never
        changes for a deployed spec."""
        with self._lock:
            state = self._deployments.get(deployment_name)
            if state is None:
                return None
            cfg = state.spec.config
            if cfg.stream_resume_arg is None:
                return None
            return (cfg.stream_resume_arg, cfg.stream_deadline_arg)

    def get_replica_actor_ids(
        self, deployment_name: Optional[str] = None
    ) -> dict[str, list[str]]:
        """deployment -> [replica actor id hex, ...] for every (or one)
        deployment — the serve-plane chaos killer targets these."""
        with self._lock:
            out: dict[str, list[str]] = {}
            for name, state in self._deployments.items():
                if deployment_name is not None and name != deployment_name:
                    continue
                ids = []
                for r in state.replicas:
                    aid = getattr(r.actor, "_actor_id", None)
                    if aid is not None:
                        ids.append(aid.hex() if isinstance(aid, bytes) else str(aid))
                out[name] = ids
            return out

    def get_version(self) -> int:
        return self._version

    def get_ingress(self, app_name: str) -> Optional[str]:
        with self._lock:
            return self._ingress.get(app_name)

    def list_apps(self) -> dict:
        with self._lock:
            return {app: list(names) for app, names in self._apps.items()}

    def get_deployment_status(self, name: str) -> dict:
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return {"exists": False}
            return {
                "exists": True,
                "target_replicas": state.target_replicas,
                "running_replicas": len([r for r in state.replicas if r.healthy]),
                "replica_ids": [r.replica_id for r in state.replicas],
            }

    def ready(self) -> bool:
        """True once every deployment has its target replica count healthy
        AND initialized (creation is async — counting replicas that are
        still running __init__ would return "ready" before a single
        request could be served)."""
        with self._lock:
            return all(
                len([r for r in s.replicas if r.healthy and r.initialized])
                >= s.target_replicas
                for s in self._deployments.values()
            )

    # -- HTTP proxy --------------------------------------------------------

    def ensure_proxy(self, port: int) -> int:
        """One ProxyActor per ALIVE node (reference: serve runs an HTTP
        proxy on every node; any proxy routes to any replica). The first
        node's proxy takes the requested port; the rest bind ephemeral
        ports (same-host test clusters can't share one). The reconcile loop
        keeps the set in sync as nodes come and go."""
        self._proxy_req_port = port
        self._ensure_proxies()
        with self._lock:
            ports = [p for _, p in self._proxies.values()]
            return ports[0] if ports else -1

    def _ensure_proxies(self) -> None:
        with self._proxy_mutex:
            self._ensure_proxies_serialized()

    def _ensure_proxies_serialized(self) -> None:
        import ray_tpu
        from ray_tpu.serve._private.proxy import ProxyActor
        from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

        if self._proxy_req_port is None:
            return
        try:
            nodes = {n["NodeID"]: n for n in ray_tpu.nodes() if n.get("Alive", True)}
        except Exception:
            return
        with self._lock:
            current = dict(self._proxies)
        # drop proxies on dead nodes
        for nid in list(current):
            if nid not in nodes:
                with self._lock:
                    self._proxies.pop(nid, None)
        # add proxies on new nodes
        for nid in nodes:
            if nid in current:
                continue
            want = self._proxy_req_port if not current and not self._proxies else 0
            cls = ray_tpu.remote(num_cpus=0)(ProxyActor)
            try:
                actor = cls.options(
                    max_concurrency=128,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(nid, soft=True),
                ).remote(want)
                p = ray_tpu.get(actor.ready.remote(), timeout=60)
            except Exception as e:
                # the node may have died between listing and placement; the
                # next reconcile tick retries — but say so, a node that can
                # never host a proxy serves no traffic
                warn_throttled(f"serve controller: proxy start on {nid}", e)
                continue
            with self._lock:
                self._proxies[nid] = (actor, p)

    def get_proxy_port(self) -> Optional[int]:
        with self._lock:
            ports = [p for _, p in self._proxies.values()]
            return ports[0] if ports else None

    def ensure_grpc_proxy(self, port: int = 0) -> int:
        """ONE gRPC ingress for the cluster (reference runs a gRPC proxy
        beside each HTTP proxy; the lite design runs a single instance —
        gRPC clients hold long-lived channels, so per-node fan-out buys
        little on the pod-scale clusters this targets)."""
        import ray_tpu
        from ray_tpu.serve._private.grpc_proxy import GrpcProxyActor

        with self._proxy_mutex:
            if self._grpc_proxy is not None:
                return self._grpc_proxy[1]
            cls = ray_tpu.remote(num_cpus=0)(GrpcProxyActor)
            actor = cls.options(max_concurrency=64).remote(port)
            p = ray_tpu.get(actor.get_port.remote(), timeout=60)
            self._grpc_proxy = (actor, p)
            return p

    def get_grpc_proxy_port(self) -> Optional[int]:
        return self._grpc_proxy[1] if self._grpc_proxy is not None else None

    def get_proxy_ports(self) -> dict:
        """node_id hex -> port, one per alive node."""
        with self._lock:
            return {nid: p for nid, (_, p) in self._proxies.items()}

    def get_ingress_info(self, app_name: str) -> Optional[dict]:
        with self._lock:
            name = self._ingress.get(app_name)
            if name is None:
                return None
            state = self._deployments.get(name)
            return {
                "deployment": name,
                "streaming": bool(state and getattr(state.spec, "streaming", False)),
                "codec": getattr(
                    getattr(state.spec, "config", None), "grpc_codec", "bytes"
                )
                if state
                else "bytes",
            }

    # -- reconciliation ----------------------------------------------------

    def _reconcile_loop(self):
        while not self._shutdown:
            try:
                self._reconcile_once()
            except Exception as e:
                warn_throttled("serve controller: reconcile", e)
            try:
                self._ensure_proxies()  # nodes come and go; proxies follow
            except Exception as e:
                warn_throttled("serve controller: ensure proxies", e)
            time.sleep(RECONCILE_PERIOD_S)

    def _reconcile_once(self):
        import ray_tpu

        with self._lock:
            states = list(self._deployments.values())
        for state in states:
            self._autoscale(state)
            with self._lock:
                spec = state.spec
                # health-check existing replicas. Replicas still running
                # __init__ (model load / jit warmup can take minutes) are
                # judged NON-BLOCKINGLY against their first check_health
                # call — pinging them with the steady-state timeout used to
                # mark every slow-init replica unhealthy and restart-loop
                # the deployment.
                for r in state.replicas:
                    if not r.initialized:
                        ready_refs, _ = ray_tpu.wait([r.init_ref], timeout=0)
                        if ready_refs:
                            try:
                                ray_tpu.get(r.init_ref, timeout=5.0)
                                r.initialized = True
                                _events.record(
                                    "serve.replica_initialized",
                                    replica=r.replica_id,
                                    init_s=round(time.time() - r.started_at, 3),
                                )
                                self._bump_version_locked()  # routers may now use it
                            except Exception as e:
                                r.healthy = False  # __init__ or first ping failed
                                _events.record(
                                    "serve.replica_unhealthy",
                                    replica=r.replica_id,
                                    reason=f"init_failed: {e!r}",
                                )
                        elif (
                            time.time() - r.started_at > REPLICA_INIT_TIMEOUT_S
                        ):
                            r.healthy = False  # wedged at init: replace it
                            _events.record(
                                "serve.replica_unhealthy",
                                replica=r.replica_id, reason="init_timeout",
                            )
                        continue
                    try:
                        ray_tpu.get(r.actor.check_health.remote(), timeout=5.0)
                    except Exception as e:
                        r.healthy = False
                        _events.record(
                            "serve.replica_unhealthy",
                            replica=r.replica_id,
                            reason=f"health_check: {e!r}",
                        )
                dead = [r for r in state.replicas if not r.healthy]
                if dead:
                    state.replicas = [r for r in state.replicas if r.healthy]
                    self._bump_version_locked()
                # start missing
                missing = state.target_replicas - len(state.replicas)
                for _ in range(max(0, missing)):
                    self._start_replica(state)
                    self._bump_version_locked()
                # stop excess (highest-index first): GRACEFUL — the victim
                # leaves the routing set now (version bump pushes the new
                # replica list to routers), but is only killed once its
                # in-flight requests finish or the grace deadline passes
                # (reference: graceful_shutdown_timeout_s drain in
                # deployment_state.py)
                excess = len(state.replicas) - state.target_replicas
                for _ in range(max(0, excess)):
                    victim = state.replicas.pop()
                    deadline = (
                        time.time() + spec.config.graceful_shutdown_timeout_s
                    )
                    _events.record(
                        "serve.replica_draining", replica=victim.replica_id,
                        deployment=spec.name,
                    )
                    state.draining.append((victim, deadline))
                    self._bump_version_locked()
            self._process_draining(state)

    def _process_draining(self, state: _DeploymentState):
        """Kill draining victims whose in-flight count hit zero (or whose
        grace deadline passed / who stopped answering)."""
        import ray_tpu

        with self._lock:
            draining = list(state.draining)
        still = []
        for victim, deadline in draining:
            done = time.time() >= deadline
            if not done:
                try:
                    m = ray_tpu.get(victim.actor.get_metrics.remote(), timeout=5.0)
                    done = m["num_ongoing_requests"] <= 0
                except Exception:
                    done = True  # unreachable: nothing left to drain
            if done:
                _events.record(
                    "serve.replica_stopped", replica=victim.replica_id,
                )
                try:
                    ray_tpu.kill(victim.actor)
                except Exception:  # raylint: disable=RL007
                    pass  # best-effort teardown: the replica may already be dead
            else:
                still.append((victim, deadline))
        with self._lock:
            state.draining = still

    def _start_replica(self, state: _DeploymentState):
        import ray_tpu
        from ray_tpu.serve._private.replica import Replica

        spec = state.spec
        rid = f"{spec.name}#{uuid.uuid4().hex[:6]}"
        cls = ray_tpu.remote(Replica)
        opts = dict(spec.config.ray_actor_options)
        # +2 headroom threads so control-plane RPCs (health, metrics,
        # reconfigure) never starve behind a saturated request queue; the
        # router enforces the actual max_ongoing_requests admission limit.
        opts["max_concurrency"] = max(spec.config.max_ongoing_requests, 1) + 2
        actor = cls.options(**opts).remote(
            rid,
            spec.callable_factory,
            spec.init_args,
            spec.init_kwargs,
            spec.config.user_config,
        )
        _events.record(
            "serve.replica_starting", replica=rid, deployment=spec.name,
        )
        state.replicas.append(
            ReplicaInfo(
                replica_id=rid,
                actor=actor,
                started_at=time.time(),
                # queued behind __init__: resolves when the replica is
                # actually constructed — the reconcile loop polls it
                # non-blockingly to flip `initialized`
                init_ref=actor.check_health.remote(),
            )
        )

    # -- autoscaling -------------------------------------------------------

    _ALERTS_REFRESH_S = 5.0

    def _firing_alerts(self) -> list[dict]:
        """FIRING SLO alerts from the head's burn-rate engine, refreshed at
        most every few seconds (best-effort: no alerts beats no autoscale
        when the head is briefly unreachable)."""
        ts, cached = self._alerts_cache
        now = time.time()
        if now - ts < self._ALERTS_REFRESH_S:
            return cached
        firing: list[dict] = []
        try:
            from ray_tpu._private.runtime import get_ctx

            firing = [
                a for a in get_ctx().call("alerts")
                if a.get("status") == "FIRING"
            ]
        except Exception as e:
            warn_throttled("serve controller: alert fetch", e)
        self._alerts_cache = (now, firing)
        return firing

    def _autoscale(self, state: _DeploymentState):
        import ray_tpu

        cfg: Optional[AutoscalingConfig] = state.spec.config.autoscaling_config
        if cfg is None:
            return
        with self._lock:
            replicas = [r for r in state.replicas if r.healthy and r.initialized]
            current = state.target_replicas
        if not replicas:
            return
        metrics = []
        for r in replicas:
            try:
                metrics.append(
                    ray_tpu.get(r.actor.get_metrics.remote(), timeout=5.0)
                )
            except Exception as e:
                # count an unreachable replica as zero load, but surface it:
                # persistently silent metrics skew autoscaling down
                warn_throttled("serve controller: replica metrics", e)
        desired = desired_replicas(
            cfg, metrics, current, alerts=tuple(self._firing_alerts())
        )
        now = time.time()
        with self._lock:
            current = state.target_replicas
            direction = (desired > current) - (desired < current)
            if direction == 0:
                state._scale_pressure_since = None
                state._scale_direction = 0
                return
            if state._scale_direction != direction:
                state._scale_direction = direction
                state._scale_pressure_since = now
                return
            delay = cfg.upscale_delay_s if direction > 0 else cfg.downscale_delay_s
            if now - (state._scale_pressure_since or now) >= delay:
                _events.record(
                    "serve.autoscale", deployment=state.spec.name,
                    from_replicas=current, to_replicas=desired,
                )
                state.target_replicas = desired
                state._scale_pressure_since = None
                state._scale_direction = 0

    # -- shutdown ----------------------------------------------------------

    def shutdown(self) -> bool:
        import ray_tpu

        with self._lock:
            self._shutdown = True
            for app in list(self._apps):
                for name in self._apps[app]:
                    self._stop_deployment(name)
            self._apps.clear()
            proxies = list(self._proxies.values())
            self._proxies.clear()
            self._proxy_req_port = None
        for actor, _port in proxies:
            try:
                ray_tpu.get(actor.stop.remote(), timeout=5)
            except Exception:  # raylint: disable=RL007
                pass  # best-effort teardown
            try:
                ray_tpu.kill(actor)
            except Exception:  # raylint: disable=RL007
                pass  # best-effort teardown
        return True

    def check_health(self) -> bool:
        return True
