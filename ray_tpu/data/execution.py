"""Streaming executor: runs a logical plan as a pipeline of remote tasks.

Reference: ``python/ray/data/_internal/execution/streaming_executor.py:55``
(scheduling loop :241), ``streaming_executor_state.py:360,501``
(backpressure-aware operator selection), ``operators/map_operator.py``
(task/actor pools), and the push-based shuffle in ``planner/exchange/``.

Design: physical operators form a tree (Union/Zip have several inputs).
Each map bundle is ONE remote task returning TWO objects — the block list
(stays remote) and its metadata list (small, fetched by the driver to make
scheduling and limit/split decisions without touching data). All-to-all ops
(shuffle/sort/repartition/groupby) are two-stage map/reduce exchanges using
``num_returns=P`` partitioned map outputs, so reducers fetch exactly their
partition — the counterpart of the reference's exchange operators.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import plan as L
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext


@dataclass
class RefBundle:
    """A unit of streaming: one remote object holding a list of blocks."""

    blocks_ref: Any  # ObjectRef -> list[Block]
    metas: list[BlockMetadata]

    @property
    def num_rows(self) -> int:
        return sum(m.num_rows for m in self.metas)

    @property
    def size_bytes(self) -> int:
        return sum(m.size_bytes for m in self.metas)


# -- remote task bodies ------------------------------------------------------


def _rechunk(blocks: list[Block], ctx_target_bytes: int, target_rows: int) -> list[Block]:
    """Merge tiny / split huge blocks toward the target size."""
    out: list[Block] = []
    pending: list[Block] = []
    pending_bytes = 0
    for b in blocks:
        acc = BlockAccessor.for_block(b)
        n, sz = acc.num_rows(), acc.size_bytes()
        if n == 0:
            continue
        if sz > ctx_target_bytes or n > target_rows:
            if pending:
                out.append(BlockAccessor.concat(pending))
                pending, pending_bytes = [], 0
            nsplits = max(int(np.ceil(sz / ctx_target_bytes)), int(np.ceil(n / target_rows)))
            per = -(-n // nsplits)
            for s in range(0, n, per):
                out.append(acc.slice(s, min(s + per, n)))
        else:
            pending.append(acc.to_arrow())
            pending_bytes += sz
            if pending_bytes >= ctx_target_bytes:
                out.append(BlockAccessor.concat(pending))
                pending, pending_bytes = [], 0
    if pending:
        out.append(BlockAccessor.concat(pending))
    return out


def _finish(blocks: list[Block], target_bytes: int, target_rows: int):
    blocks = _rechunk(blocks, target_bytes, target_rows)
    metas = [BlockAccessor.for_block(b).get_metadata() for b in blocks]
    return blocks, metas


def _run_read_task(read_task, target_bytes: int, target_rows: int):
    return _finish(list(read_task()), target_bytes, target_rows)


def _stream_read_task(read_task, target_bytes: int, target_rows: int):
    """Streaming read body (num_returns="streaming"): yield one
    (blocks_ref, metas) bundle per ~target_bytes of input as the datasource
    produces blocks, so downstream map stages start while the read is still
    running (reference: read tasks as streaming generators feeding the
    executor's block queue). Blocks are put from the worker; only the small
    (ref, metas) tuple rides the stream."""
    import ray_tpu

    yielded = False
    # one bundle per datasource-yielded block (split to target size if the
    # block is huge) — the yield boundary IS the streaming unit, like the
    # reference's dynamic block splitting; downstream consolidation happens
    # in the map stages' own _finish/_rechunk
    for block in read_task():
        blocks, metas = _finish([block], target_bytes, target_rows)
        yield (ray_tpu.put(blocks), metas)
        yielded = True
    if not yielded:  # empty source still emits one (empty) bundle
        blocks, metas = _finish([], target_bytes, target_rows)
        yield (ray_tpu.put(blocks), metas)


def _run_map_task(transform, blocks: list[Block], target_bytes: int, target_rows: int):
    return _finish(list(transform(iter(blocks))), target_bytes, target_rows)


def _slice_rows(all_blocks: list[list[Block]], start: int, end: int):
    """Row-range slice across an ordered list of bundles (repartition/zip)."""
    flat: list[Block] = [b for blocks in all_blocks for b in blocks]
    out: list[Block] = []
    offset = 0
    for b in flat:
        acc = BlockAccessor.for_block(b)
        n = acc.num_rows()
        lo, hi = max(start - offset, 0), min(end - offset, n)
        if lo < hi:
            out.append(acc.slice(lo, hi))
        offset += n
        if offset >= end:
            break
    return BlockAccessor.concat(out)


class MapTransform:
    """Picklable fused transform: Iterator[Block] -> Iterator[Block].

    Built from a MapChain of logical one-to-one ops. Class-based UDFs are
    instantiated once per worker (actor) via ``prepare()``.
    """

    def __init__(self, ops: list[L.AbstractMap]):
        self.ops = ops
        self._instances: Optional[list[Callable]] = None

    def prepare(self):
        if self._instances is None:
            inst = []
            for op in self.ops:
                fn = op.fn
                if isinstance(fn, type):
                    fn = fn(*op.fn_constructor_args, **op.fn_constructor_kwargs)
                inst.append(fn)
            self._instances = inst
        return self

    def __call__(self, blocks: Iterator[Block]) -> Iterator[Block]:
        self.prepare()
        for op, fn in zip(self.ops, self._instances):
            blocks = self._apply_one(op, fn, blocks)
        return blocks

    def _apply_one(self, op, fn, blocks: Iterator[Block]) -> Iterator[Block]:
        if isinstance(op, L.MapBatches):
            return self._apply_batches(op, fn, blocks)
        if isinstance(op, L.Filter):
            return self._apply_rows(blocks, lambda rows: (r for r in rows if fn(r, *op.fn_args, **op.fn_kwargs)))
        if isinstance(op, L.FlatMap):
            return self._apply_rows(
                blocks, lambda rows: (o for r in rows for o in fn(r, *op.fn_args, **op.fn_kwargs))
            )
        if isinstance(op, (L.MapRows, L.Project)):
            return self._apply_rows(blocks, lambda rows: (fn(r, *op.fn_args, **op.fn_kwargs) for r in rows))
        raise TypeError(f"Unknown map op {op}")

    @staticmethod
    def _apply_rows(blocks, gen):
        for b in blocks:
            rows = list(gen(BlockAccessor.for_block(b).iter_rows()))
            yield BlockAccessor.rows_to_block(rows)

    @staticmethod
    def _apply_batches(op: L.MapBatches, fn, blocks):
        def to_format(block):
            acc = BlockAccessor.for_block(block)
            if op.batch_format in ("numpy", None, "default"):
                return acc.to_numpy_batch()
            if op.batch_format == "pandas":
                return acc.to_pandas()
            if op.batch_format == "pyarrow":
                return acc.to_arrow()
            raise ValueError(f"Unknown batch_format {op.batch_format!r}")

        if op.batch_size is None:
            for b in blocks:
                if BlockAccessor.for_block(b).num_rows() == 0:
                    continue
                out = fn(to_format(b), *op.fn_args, **op.fn_kwargs)
                yield from _coerce_batch_out(out)
            return
        # Re-batch across block boundaries to exactly batch_size rows.
        buf: list[Block] = []
        buffered = 0
        for b in blocks:
            acc = BlockAccessor.for_block(b)
            if acc.num_rows() == 0:
                continue
            buf.append(acc.to_arrow())
            buffered += acc.num_rows()
            while buffered >= op.batch_size:
                merged = BlockAccessor.concat(buf)
                macc = BlockAccessor.for_block(merged)
                head = macc.slice(0, op.batch_size)
                rest_n = macc.num_rows() - op.batch_size
                buf = [macc.slice(op.batch_size, macc.num_rows())] if rest_n else []
                buffered = rest_n
                out = fn(to_format(head), *op.fn_args, **op.fn_kwargs)
                yield from _coerce_batch_out(out)
        if buffered:
            merged = BlockAccessor.concat(buf)
            out = fn(to_format(merged), *op.fn_args, **op.fn_kwargs)
            yield from _coerce_batch_out(out)


def _coerce_batch_out(out) -> Iterator[Block]:
    import types

    if isinstance(out, types.GeneratorType):
        for o in out:
            yield BlockAccessor.batch_to_block(o)
    else:
        yield BlockAccessor.batch_to_block(out)


class _MapWorker:
    """Actor body for ActorPoolMapOperator (reference:
    ``operators/actor_pool_map_operator.py``)."""

    def __init__(self, transform: MapTransform):
        self.transform = transform.prepare()

    def ready(self) -> bool:
        return True

    def apply(self, blocks: list[Block], target_bytes: int, target_rows: int):
        return _finish(list(self.transform(iter(blocks))), target_bytes, target_rows)


# -- physical operators ------------------------------------------------------


class PhysicalOp:
    def __init__(self, name: str, inputs: list["PhysicalOp"]):
        self.name = name
        self.inputs = inputs
        self.input_queue: collections.deque[RefBundle] = collections.deque()
        self.output_queue: collections.deque[RefBundle] = collections.deque()
        self.inputs_done = False
        self.finished = False
        # in-flight: meta_ref -> (blocks_ref, extra)
        self.pending: dict[Any, tuple] = {}
        # Datasets are ordered: tasks may COMPLETE out of order but bundles
        # are emitted in dispatch order (reference: preserve_order semantics
        # of the streaming executor for sort/repartition correctness).
        self._order: collections.deque = collections.deque()
        self._done_buf: dict[Any, RefBundle] = {}

    def can_dispatch(self, ctx: DataContext) -> bool:
        return bool(self.input_queue) and len(self.pending) < ctx.max_tasks_per_op

    def dispatch(self, ctx: DataContext):
        raise NotImplementedError

    def _track(self, meta_ref, blocks_ref):
        self.pending[meta_ref] = (blocks_ref, None)
        self._order.append(meta_ref)

    def on_task_done(self, meta_ref, ctx: DataContext):
        blocks_ref, _ = self.pending.pop(meta_ref)
        metas = ray_tpu.get(meta_ref)
        self._done_buf[meta_ref] = RefBundle(blocks_ref, metas)
        while self._order and self._order[0] in self._done_buf:
            self.output_queue.append(self._done_buf.pop(self._order.popleft()))

    def maybe_finish(self):
        if self.inputs_done and not self.input_queue and not self.pending:
            self.finished = True

    def poll(self, ctx: DataContext) -> None:
        """Called every loop step: ops with out-of-band progress (streaming
        reads) move it into output_queue here."""

    def shutdown(self):
        pass

    def buffered_output_bytes(self) -> int:
        return sum(b.size_bytes for b in self.output_queue)

    def queued_bytes(self) -> int:
        """Un-consumed bytes parked at this op (input + output queues) —
        the quantity global backpressure must bound."""
        return sum(b.size_bytes for b in self.input_queue) + self.buffered_output_bytes()


class InputOp(PhysicalOp):
    """Feeds pre-existing bundles (InputData / materialized datasets)."""

    def __init__(self, bundles: list[RefBundle]):
        super().__init__("Input", [])
        self.output_queue.extend(bundles)
        self.inputs_done = True
        self.finished = True


class ReadOp(PhysicalOp):
    """Reads stream: each read task runs as a streaming-generator task whose
    items become bundles as the datasource produces blocks — downstream
    stages start on a big file's first blocks while its tail is still being
    read. Bundles still emit in dispatch order (ordered-dataset semantics):
    the front stream flows through immediately; later streams buffer until
    it finishes."""

    def __init__(self, read_tasks: list, remote_opts: dict):
        super().__init__("Read", [])
        self._tasks = collections.deque(read_tasks)
        self.inputs_done = True
        self._remote = ray_tpu.remote(_stream_read_task).options(
            num_returns="streaming", **remote_opts
        )
        import threading

        self._slock = threading.Lock()
        self._streams: collections.deque[dict] = collections.deque()

    def can_dispatch(self, ctx):
        return bool(self._tasks) and len(self._streams) < ctx.max_tasks_per_op

    def dispatch(self, ctx):
        rt = self._tasks.popleft()
        rec = {
            "rt": rt, "buf": collections.deque(), "done": False, "err": None,
            # operator-level fault tolerance: streams are never replayed by
            # the core (a consumer may have seen items of the dead run), so
            # the READ OP re-runs the deterministic read task itself and
            # skips the bundles it already emitted — the data-plane analog
            # of lineage reconstruction (reference: Ray Data retries failed
            # read/map tasks at the operator layer)
            "emitted": 0, "retries": 3, "epoch": 0,
            "ctx_args": (ctx.target_max_block_size, ctx.target_max_rows_per_block),
        }
        with self._slock:
            self._streams.append(rec)
        self._spawn_feed(rec)

    def _spawn_feed(self, rec):
        import threading

        old = rec.get("gen")
        if old is not None:
            try:
                old.close()  # dispose the superseded stream + its producer
            except Exception:
                pass
        gen = self._remote.remote(rec["rt"], *rec["ctx_args"])
        rec["gen"] = gen
        threading.Thread(
            target=self._feed, args=(gen, rec, rec["emitted"], rec["epoch"]),
            name="read-stream-feed", daemon=True,
        ).start()

    def _feed(self, gen, rec, skip: int, epoch: int):
        """All rec mutations are epoch-guarded under _slock: a superseded
        feed thread (its stream was retried) must never mark the fresh
        epoch done/errored or append stale bundles."""
        try:
            for item_ref in gen:
                blocks_ref, metas = ray_tpu.get(item_ref)
                with self._slock:
                    if rec["epoch"] != epoch:
                        return  # retried underneath us: hand over entirely
                    if skip > 0:
                        skip -= 1  # replay of an already-emitted bundle
                        continue
                    rec["buf"].append(RefBundle(blocks_ref, metas))
        except BaseException as e:  # noqa: BLE001 - surfaced in poll()
            with self._slock:
                if rec["epoch"] == epoch:
                    rec["err"] = e
        finally:
            with self._slock:
                if rec["epoch"] == epoch:
                    rec["done"] = True

    @staticmethod
    def _retriable(err) -> bool:
        from ray_tpu import exceptions as rex

        return isinstance(
            err, (rex.WorkerCrashedError, rex.RayActorError, rex.ObjectLostError)
        )

    def poll(self, ctx):
        if self.finished:
            self.shutdown()
            return
        err = None
        respawn = None
        with self._slock:
            while self._streams:
                rec = self._streams[0]
                while rec["buf"]:
                    self.output_queue.append(rec["buf"].popleft())
                    rec["emitted"] += 1
                if rec["err"] is not None:
                    if self._retriable(rec["err"]) and rec["retries"] != 0:
                        if rec["retries"] > 0:
                            rec["retries"] -= 1
                        rec["err"] = None
                        rec["done"] = False
                        rec["buf"].clear()
                        rec["epoch"] += 1  # invalidates the old feed thread
                        respawn = rec
                    else:
                        err = rec["err"]
                    break
                if rec["done"]:
                    self._streams.popleft()
                    continue
                break
        if respawn is not None:
            self._spawn_feed(respawn)  # outside _slock: submits a task
        if err is not None:
            raise err

    def maybe_finish(self):
        if not self._tasks and not self._streams and not self.pending:
            self.finished = True

    def shutdown(self):
        from ray_tpu._private.log_util import warn_throttled

        with self._slock:
            for rec in self._streams:
                try:
                    rec["gen"].close()
                except Exception as e:
                    # the producer may already be dead (its items consumed);
                    # log so a systematically failing dispose isn't silent
                    warn_throttled("data read op: stream dispose", e)
            self._streams.clear()


class TaskMapOp(PhysicalOp):
    def __init__(self, name: str, transform: MapTransform, remote_opts: dict):
        super().__init__(name, [])
        self.transform = transform
        self._remote = ray_tpu.remote(_run_map_task).options(num_returns=2, **remote_opts)

    def dispatch(self, ctx):
        bundle = self.input_queue.popleft()
        blocks_ref, meta_ref = self._remote.remote(
            self.transform, bundle.blocks_ref, ctx.target_max_block_size, ctx.target_max_rows_per_block
        )
        self._track(meta_ref, blocks_ref)


class ActorMapOp(PhysicalOp):
    """Fixed-size actor pool; bundles go to the least-loaded ready actor."""

    def __init__(self, name: str, transform: MapTransform, pool_size: int, remote_opts: dict):
        super().__init__(name, [])
        actor_cls = ray_tpu.remote(_MapWorker).options(**remote_opts)
        self._actors = [actor_cls.remote(transform) for _ in range(pool_size)]
        for a in self._actors:
            a.ready.remote()
        self._load = {i: 0 for i in range(pool_size)}
        self._by_meta: dict[Any, int] = {}

    def can_dispatch(self, ctx):
        return bool(self.input_queue) and any(
            v < ctx.max_tasks_in_flight_per_actor for v in self._load.values()
        )

    def dispatch(self, ctx):
        bundle = self.input_queue.popleft()
        idx = min(self._load, key=self._load.get)
        blocks_ref, meta_ref = self._actors[idx].apply.options(num_returns=2).remote(
            bundle.blocks_ref, ctx.target_max_block_size, ctx.target_max_rows_per_block
        )
        self._track(meta_ref, blocks_ref)
        self._load[idx] += 1
        self._by_meta[meta_ref] = idx

    def on_task_done(self, meta_ref, ctx):
        self._load[self._by_meta.pop(meta_ref)] -= 1
        super().on_task_done(meta_ref, ctx)

    def shutdown(self):
        from ray_tpu._private.log_util import warn_throttled

        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception as e:
                # best-effort teardown (the actor may already be gone), but
                # a kill that ALWAYS fails leaks pool actors — say so
                warn_throttled("data actor-map op: actor kill", e)


class LimitOp(PhysicalOp):
    """Driver-side limit using metadata only; slices the boundary bundle."""

    def __init__(self, limit: int):
        super().__init__(f"Limit({limit})", [])
        self._remaining = limit

    def can_dispatch(self, ctx):
        return bool(self.input_queue)

    def dispatch(self, ctx):
        bundle = self.input_queue.popleft()
        if self._remaining <= 0:
            return
        if bundle.num_rows <= self._remaining:
            self._remaining -= bundle.num_rows
            self.output_queue.append(bundle)
        else:
            take = self._remaining
            self._remaining = 0
            blocks_ref, meta_ref = (
                ray_tpu.remote(_limit_task).options(num_returns=2).remote(bundle.blocks_ref, take)
            )
            self._track(meta_ref, blocks_ref)
        if self._remaining <= 0:
            self.input_queue.clear()
            self.inputs_done = True

    def maybe_finish(self):
        if self.inputs_done and not self.input_queue and not self.pending:
            self.finished = True

    @property
    def satisfied(self) -> bool:
        return self._remaining <= 0


def _limit_task(blocks: list[Block], take: int):
    out = []
    for b in blocks:
        acc = BlockAccessor.for_block(b)
        n = acc.num_rows()
        if take <= 0:
            break
        out.append(acc.slice(0, min(take, n)))
        take -= n
    blocks = out
    return blocks, [BlockAccessor.for_block(b).get_metadata() for b in blocks]


class AllToAllOp(PhysicalOp):
    """Barrier exchange: collects every input bundle, then runs a two-stage
    map/reduce plan (reference: ``planner/exchange`` + shuffle ops)."""

    def __init__(self, kind: str, options: dict):
        super().__init__(kind, [])
        self.kind = kind
        self.options = options
        self._collected: list[RefBundle] = []
        self._launched = False

    def can_dispatch(self, ctx):
        return bool(self.input_queue) or (
            self.inputs_done and not self._launched and not self.pending
        )

    def dispatch(self, ctx):
        while self.input_queue:
            self._collected.append(self.input_queue.popleft())
        if self.inputs_done and not self._launched:
            self._launched = True
            self._launch(ctx)

    def _launch(self, ctx: DataContext):
        from ray_tpu.data import exchange

        bundles = self._collected
        for blocks_ref, meta_ref in exchange.launch(self.kind, bundles, self.options, ctx):
            self._track(meta_ref, blocks_ref)

    def maybe_finish(self):
        if self.inputs_done and self._launched and not self.pending and not self.input_queue:
            self.finished = True


class UnionOp(PhysicalOp):
    """Concatenation preserving dataset order: child i's bundles are emitted
    only after every child < i has finished."""

    def __init__(self, name, inputs):
        super().__init__(name, inputs)
        self.per_child: list[collections.deque] = []

    def can_dispatch(self, ctx):
        return any(self.per_child)

    def dispatch(self, ctx):
        for i, q in enumerate(self.per_child):
            while q:
                self.output_queue.append(q.popleft())
            if not self.inputs[i].finished:
                break

    def maybe_finish(self):
        if self.inputs_done and not any(self.per_child) and not self.pending:
            self.finished = True

    def queued_bytes(self) -> int:
        return super().queued_bytes() + sum(b.size_bytes for q in self.per_child for b in q)


class ZipOp(PhysicalOp):
    """Barrier both sides; zip by row ranges (reference: Zip op)."""

    def __init__(self):
        super().__init__("Zip", [])
        self.left: list[RefBundle] = []
        self.right: list[RefBundle] = []
        self._launched = False

    def can_dispatch(self, ctx):
        return self.inputs_done and not self._launched

    def dispatch(self, ctx):
        self._launched = True
        lrefs = [b.blocks_ref for b in self.left]
        rrefs = [b.blocks_ref for b in self.right]
        n_left = sum(b.num_rows for b in self.left)
        n_right = sum(b.num_rows for b in self.right)
        if n_left != n_right:
            raise ValueError(f"zip(): datasets have different row counts ({n_left} vs {n_right})")
        nparts = max(1, min(len(self.left), ctx.max_shuffle_partitions))
        per = -(-n_left // nparts)
        remote = ray_tpu.remote(_zip_task).options(num_returns=2)
        for i in range(nparts):
            start, end = i * per, min((i + 1) * per, n_left)
            if start >= end:
                break
            blocks_ref, meta_ref = remote.remote(start, end, len(lrefs), *lrefs, *rrefs)
            self._track(meta_ref, blocks_ref)

    def maybe_finish(self):
        if self._launched and not self.pending:
            self.finished = True

    def queued_bytes(self) -> int:
        return super().queued_bytes() + sum(b.size_bytes for b in self.left + self.right)


def _zip_task(start: int, end: int, n_left: int, *all_blocks):
    left = _slice_rows(list(all_blocks[:n_left]), start, end)
    right = _slice_rows(list(all_blocks[n_left:]), start, end)
    import pyarrow as pa

    lt = BlockAccessor.for_block(left).to_arrow()
    rt = BlockAccessor.for_block(right).to_arrow()
    lmeta, rmeta = lt.schema.metadata or {}, rt.schema.metadata or {}
    cols = {n: lt.column(n) for n in lt.column_names}
    meta = dict(lmeta)
    for n in rt.column_names:
        # Disambiguate duplicates without clobbering existing left columns,
        # and remap per-column tensor_shape metadata to the final name.
        name = n
        suffix = 1
        while name in cols:
            name = f"{n}_{suffix}"
            suffix += 1
        cols[name] = rt.column(n)
        shape_key = f"tensor_shape:{n}".encode()
        if shape_key in rmeta:
            meta[f"tensor_shape:{name}".encode()] = rmeta[shape_key]
    t = pa.table(cols)
    if meta:
        t = t.replace_schema_metadata(meta)
    blocks = [t]
    return blocks, [BlockAccessor.for_block(b).get_metadata() for b in blocks]


# -- executor ----------------------------------------------------------------


def build_physical(plan: L.LogicalPlan, ctx: DataContext) -> list[PhysicalOp]:
    """Lower an (optimized) logical plan to a physical op chain (topological
    order: producers before consumers). Child plans of Union/Zip are lowered
    recursively and wired into the consumer's `inputs`."""
    if ctx.enable_operator_fusion:
        plan = plan.optimized()
    ops: list[PhysicalOp] = []
    prev: Optional[PhysicalOp] = None
    for lop in plan.ops:
        if isinstance(lop, L.Read):
            if lop.parallelism > 0:
                parallelism = lop.parallelism
            elif ctx.read_parallelism > 0:
                parallelism = ctx.read_parallelism
            else:
                parallelism = ctx.min_parallelism
            read_tasks = lop.datasource.get_read_tasks(parallelism)
            cur = ReadOp(read_tasks, {})
        elif isinstance(lop, L.InputData):
            cur = InputOp(lop.bundles)
        elif isinstance(lop, L.MapChain):
            cur = _lower_map(lop.ops, lop.name, ctx)
        elif isinstance(lop, L.AbstractMap):
            cur = _lower_map([lop], lop.name, ctx)
        elif isinstance(lop, L.Limit):
            cur = LimitOp(lop.limit)
        elif isinstance(lop, L.AllToAll):
            cur = AllToAllOp(lop.kind, lop.options)
        elif isinstance(lop, L.Union):
            cur = UnionOp("Union", [])
            for child in lop.others:
                child_ops = build_physical(child, ctx)
                ops.extend(child_ops)
                cur.inputs.append(child_ops[-1])
        elif isinstance(lop, L.Zip):
            cur = ZipOp()
            child_ops = build_physical(lop.other, ctx)
            ops.extend(child_ops)
            cur.inputs.append(child_ops[-1])
        else:
            raise TypeError(f"Cannot lower {lop}")
        if prev is not None:
            cur.inputs.insert(0, prev)
        ops.append(cur)
        prev = cur
    return ops


def _lower_map(lops: list[L.AbstractMap], name: str, ctx: DataContext) -> PhysicalOp:
    transform = MapTransform(lops)
    opts = {}
    head = lops[0]
    if head.num_cpus is not None:
        opts["num_cpus"] = head.num_cpus
    if head.num_tpus is not None:
        opts["num_tpus"] = head.num_tpus
    if any(op.uses_actors() for op in lops):
        conc = head.concurrency or 2
        if isinstance(conc, (tuple, list)):
            conc = conc[-1]
        return ActorMapOp(name, transform, int(conc), opts)
    return TaskMapOp(name, transform, opts)


class StreamingExecutor:
    """Pull-based scheduling loop yielding output bundles as they finish.

    Reference: ``StreamingExecutor.run`` loop ``_scheduling_loop_step``
    (``streaming_executor.py:241``): dispatch on the runnable op with the
    least buffered output (backpressure), then harvest completions via
    ``ray_tpu.wait``.
    """

    def __init__(self, plan: L.LogicalPlan, ctx: Optional[DataContext] = None):
        self.ctx = ctx or DataContext.get_current()
        self.ops = build_physical(plan, self.ctx)
        self.final = self.ops[-1]

    def __iter__(self) -> Iterator[RefBundle]:
        try:
            yield from self._run()
        finally:
            self.shutdown()

    def shutdown(self):
        for op in self.ops:
            op.shutdown()

    def _move_edges(self):
        moved = False
        for op in self.ops:
            if isinstance(op, UnionOp) and not op.per_child:
                op.per_child = [collections.deque() for _ in op.inputs]
            for i, parent in enumerate(op.inputs):
                if isinstance(op, ZipOp):
                    side = op.left if parent is op.inputs[0] else op.right
                    while parent.output_queue:
                        side.append(parent.output_queue.popleft())
                        moved = True
                elif isinstance(op, UnionOp):
                    while parent.output_queue:
                        op.per_child[i].append(parent.output_queue.popleft())
                        moved = True
                else:
                    while parent.output_queue:
                        op.input_queue.append(parent.output_queue.popleft())
                        moved = True
            if op.inputs and all(p.finished for p in op.inputs):
                if not op.inputs_done:
                    moved = True
                op.inputs_done = True
        return moved

    def _upstream(self, op: PhysicalOp) -> list[PhysicalOp]:
        out, stack = [], list(op.inputs)
        while stack:
            cur = stack.pop()
            out.append(cur)
            stack.extend(cur.inputs)
        return out

    def _cancel_satisfied_limits(self):
        """Once a Limit has its rows, stop feeding it: mark every upstream op
        finished and drop its queued/pending work (the reference's executor
        propagates completion upstream of a satisfied limit the same way)."""
        for op in self.ops:
            if isinstance(op, LimitOp) and op.satisfied:
                for up in self._upstream(op):
                    if not up.finished:
                        up.finished = True
                        up.inputs_done = True
                        up.input_queue.clear()
                        up.output_queue.clear()
                        up.pending.clear()
                        up._order.clear()
                        up._done_buf.clear()
                        if isinstance(up, ReadOp):
                            up._tasks.clear()
                        up.shutdown()

    def _run(self) -> Iterator[RefBundle]:
        ctx = self.ctx
        while True:
            self._move_edges()
            self._cancel_satisfied_limits()
            # Dispatch: runnable ops, least-buffered-output first.
            runnable = [op for op in self.ops if not op.finished and op.can_dispatch(ctx)]
            runnable.sort(key=lambda o: o.queued_bytes())
            dispatched = False
            buffered = sum(o.queued_bytes() for o in self.ops)
            for op in runnable:
                if buffered > ctx.max_buffered_bytes and isinstance(op, (ReadOp, InputOp)):
                    continue  # backpressure: stop ingesting, keep draining
                op.dispatch(ctx)
                dispatched = True
            for op in self.ops:
                op.poll(ctx)
            # Harvest completions.
            pending = [(ref, op) for op in self.ops for ref in op.pending]
            if pending:
                refs = [r for r, _ in pending]
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0.02)
                owner = dict(pending)
                for ref in ready:
                    owner[ref].on_task_done(ref, ctx)
            for op in self.ops:
                op.maybe_finish()
            self._move_edges()
            while self.final.output_queue:
                yield self.final.output_queue.popleft()
            if self.final.finished:
                return
            if not dispatched and not pending:
                # Nothing running and nothing to do: either done or stalled.
                if all(op.finished for op in self.ops):
                    return
                time.sleep(0.005)


def execute_to_bundles(plan: L.LogicalPlan, ctx: Optional[DataContext] = None) -> list[RefBundle]:
    return list(StreamingExecutor(plan, ctx))
