"""All-to-all exchanges: repartition, random_shuffle, sort, groupby/aggregate.

Reference: ``python/ray/data/_internal/planner/exchange/`` (push-based
shuffle: partition map tasks + reduce tasks). Map tasks here use
``num_returns=P`` so each reducer fetches exactly its partition's objects —
no broadcast of the whole shuffle through one process.
"""

from __future__ import annotations

from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, _arrow_col_to_numpy
from ray_tpu.data.context import DataContext


def launch(kind: str, bundles: list, options: dict, ctx: DataContext):
    """Returns a list of (blocks_ref, meta_ref) for the reduce tasks."""
    if not bundles:
        return []
    if kind == "repartition":
        return _repartition(bundles, options["num_blocks"], ctx)
    if kind == "random_shuffle":
        return _shuffle(bundles, options.get("seed"), ctx)
    if kind == "sort":
        return _sort(bundles, options["key"], options.get("descending", False), ctx)
    if kind == "aggregate":
        return _aggregate(bundles, options.get("key"), options["aggs"], ctx)
    if kind == "map_groups":
        return _map_groups(bundles, options["key"], options["fn"], options.get("batch_format", "numpy"), ctx)
    raise ValueError(f"Unknown all-to-all kind {kind!r}")


def _num_partitions(bundles, ctx) -> int:
    return max(1, min(len(bundles), ctx.max_shuffle_partitions))


def _stable_hash(v) -> int:
    """Deterministic across processes (Python's hash() is salted per process;
    worker processes would route the same string key to different partitions)."""
    import zlib

    if isinstance(v, bytes):
        data = v
    elif isinstance(v, str):
        data = v.encode()
    else:
        data = repr(v).encode()
    return zlib.crc32(data)


# -- repartition -------------------------------------------------------------


def _repartition(bundles, num_blocks: int, ctx):
    total = sum(b.num_rows for b in bundles)
    per = -(-total // num_blocks) if total else 0
    # Only ship the bundles overlapping each output row range.
    offsets = np.cumsum([0] + [b.num_rows for b in bundles])
    remote = ray_tpu.remote(_repartition_reduce).options(num_returns=2)
    out = []
    for i in range(num_blocks):
        start, end = i * per, min((i + 1) * per, total)
        if start >= end and total:
            # Emit an empty block to honor the requested count.
            start = end = total
        sel = [
            (b.blocks_ref, int(offsets[j]))
            for j, b in enumerate(bundles)
            if offsets[j + 1] > start and offsets[j] < end
        ] or [(bundles[0].blocks_ref, 0)]
        refs = [r for r, _ in sel]
        base = sel[0][1]
        out.append(remote.remote(start - base, end - base, *refs))
    return out


def _repartition_reduce(start: int, end: int, *all_blocks):
    from ray_tpu.data.execution import _slice_rows

    block = _slice_rows(list(all_blocks), start, end)
    return [block], [BlockAccessor.for_block(block).get_metadata()]


# -- random shuffle ----------------------------------------------------------


def _shuffle(bundles, seed, ctx):
    P = _num_partitions(bundles, ctx)
    part = ray_tpu.remote(_shuffle_map).options(num_returns=P)
    cols = [part.remote(b.blocks_ref, P, seed, i) for i, b in enumerate(bundles)]
    reduce = ray_tpu.remote(_shuffle_reduce).options(num_returns=2)
    out = []
    for p in range(P):
        out.append(reduce.remote(seed, p, *[c[p] if P > 1 else c for c in cols]))
    return out


def _shuffle_map(blocks: list[Block], P: int, seed, salt: int):
    t = BlockAccessor.concat(blocks)
    acc = BlockAccessor.for_block(t)
    n = acc.num_rows()
    rng = np.random.default_rng(None if seed is None else seed + salt)
    assign = rng.integers(0, P, size=n)
    parts = []
    for p in range(P):
        idx = np.nonzero(assign == p)[0]
        parts.append(acc.take_indices(idx))
    return tuple(parts) if P > 1 else parts[0]


def _shuffle_reduce(seed, salt: int, *parts):
    t = BlockAccessor.concat(list(parts))
    acc = BlockAccessor.for_block(t)
    rng = np.random.default_rng(None if seed is None else seed * 7919 + salt)
    perm = rng.permutation(acc.num_rows())
    block = acc.take_indices(perm)
    return [block], [BlockAccessor.for_block(block).get_metadata()]


# -- sort --------------------------------------------------------------------


def _sort(bundles, key, descending: bool, ctx):
    P = _num_partitions(bundles, ctx)
    keys = [key] if isinstance(key, str) else list(key)
    primary = keys[0]
    # Stage 0: sample to pick range boundaries (reference: SortTaskSpec
    # sample_boundaries).
    sampler = ray_tpu.remote(_sort_sample)
    samples = ray_tpu.get([sampler.remote(b.blocks_ref, primary) for b in bundles])
    nonempty = [s for s in samples if len(s)]
    allv = np.concatenate(nonempty) if nonempty else np.array([])
    if len(allv) == 0:
        P = 1
        boundaries = np.array([])
    else:
        allv = np.sort(allv)
        qs = np.linspace(0, 1, P + 1)[1:-1]
        boundaries = np.quantile(allv, qs) if np.issubdtype(allv.dtype, np.number) else np.array(
            [allv[int(q * (len(allv) - 1))] for q in qs]
        )
    part = ray_tpu.remote(_sort_map).options(num_returns=max(P, 1))
    cols = [part.remote(b.blocks_ref, primary, boundaries, descending) for b in bundles]
    reduce = ray_tpu.remote(_sort_reduce).options(num_returns=2)
    out = []
    order = range(P - 1, -1, -1) if descending else range(P)
    for p in order:
        out.append(reduce.remote(keys, descending, *[c[p] if P > 1 else c for c in cols]))
    return out


def _sort_sample(blocks: list[Block], key: str):
    t = BlockAccessor.concat(blocks)
    tab = BlockAccessor.for_block(t).to_arrow()
    if tab.num_rows == 0 or key not in tab.column_names:
        return np.array([])
    col = _arrow_col_to_numpy(tab, key)
    if len(col) > 200:
        col = np.random.default_rng(0).choice(col, 200, replace=False)
    return col


def _sort_map(blocks: list[Block], key: str, boundaries: np.ndarray, descending: bool):
    t = BlockAccessor.concat(blocks)
    acc = BlockAccessor.for_block(t)
    P = len(boundaries) + 1
    if P == 1:
        return t
    tab = acc.to_arrow()
    if tab.num_rows == 0:
        return tuple(tab for _ in range(P))
    col = _arrow_col_to_numpy(tab, key)
    assign = np.searchsorted(boundaries, col, side="right")
    parts = [acc.take_indices(np.nonzero(assign == p)[0]) for p in range(P)]
    return tuple(parts)


def _sort_reduce(keys: list[str], descending: bool, *parts):
    t = BlockAccessor.concat(list(parts))
    acc = BlockAccessor.for_block(t)
    tab = acc.to_arrow()
    if tab.num_rows:
        order = "descending" if descending else "ascending"
        tab = tab.sort_by([(k, order) for k in keys])
    return [tab], [BlockAccessor.for_block(tab).get_metadata()]


# -- groupby / aggregate -----------------------------------------------------


def _aggregate(bundles, key, aggs, ctx):
    from ray_tpu.data.aggregate import AggregateFn

    aggs = list(aggs)
    if key is None:
        # Global aggregation: per-bundle partial states + one combine task.
        part = ray_tpu.remote(_agg_partial)
        partials = [part.remote(b.blocks_ref, None, aggs) for b in bundles]
        final = ray_tpu.remote(_agg_finalize).options(num_returns=2)
        return [final.remote(None, aggs, *partials)]
    P = _num_partitions(bundles, ctx)
    part = ray_tpu.remote(_agg_hash_partial).options(num_returns=P)
    cols = [part.remote(b.blocks_ref, key, aggs, P) for b in bundles]
    final = ray_tpu.remote(_agg_finalize).options(num_returns=2)
    return [final.remote(key, aggs, *[c[p] if P > 1 else c for c in cols]) for p in range(P)]


def _group_partials(t, key, aggs):
    """block → {group_key_tuple: [state, ...]} partial aggregation."""
    acc = BlockAccessor.for_block(t)
    batch = acc.to_numpy_batch()
    states: dict[Any, list] = {}
    if acc.num_rows() == 0:
        return states
    if key is None:
        groups = {None: np.arange(acc.num_rows())}
    else:
        col = batch[key]
        uniq, inv = np.unique(col, return_inverse=True)
        groups = {uniq[i].item() if hasattr(uniq[i], "item") else uniq[i]: np.nonzero(inv == i)[0] for i in range(len(uniq))}
    for gk, idx in groups.items():
        sub = {k: v[idx] for k, v in batch.items()}
        states[gk] = [a.partial(sub) for a in aggs]
    return states


def _merge_states(all_states: list[dict], aggs):
    merged: dict[Any, list] = {}
    for states in all_states:
        for gk, st in states.items():
            if gk not in merged:
                merged[gk] = st
            else:
                merged[gk] = [a.merge(x, y) for a, x, y in zip(aggs, merged[gk], st)]
    return merged


def _agg_partial(blocks: list[Block], key, aggs):
    return _group_partials(BlockAccessor.concat(blocks), key, aggs)


def _agg_hash_partial(blocks: list[Block], key, aggs, P: int):
    t = BlockAccessor.concat(blocks)
    states = _group_partials(t, key, aggs)
    parts: list[dict] = [{} for _ in range(P)]
    for gk, st in states.items():
        parts[_stable_hash(gk) % P][gk] = st
    return tuple(parts) if P > 1 else parts[0]


def _agg_finalize(key, aggs, *all_states):
    merged = _merge_states(list(all_states), aggs)
    rows = []
    for gk in sorted(merged, key=lambda x: (x is None, x)):
        row = {} if key is None else {key: gk}
        for a, st in zip(aggs, merged[gk]):
            row[a.name] = a.finalize(st)
        rows.append(row)
    block = BlockAccessor.rows_to_block(rows)
    return [block], [BlockAccessor.for_block(block).get_metadata()]


# -- map_groups --------------------------------------------------------------


def _map_groups(bundles, key, fn, batch_format, ctx):
    """GroupedData.map_groups: hash-partition rows by key, then apply ``fn``
    to each whole group (reference: ``grouped_data.py`` map_groups)."""
    P = _num_partitions(bundles, ctx)
    part = ray_tpu.remote(_hash_partition_rows).options(num_returns=P)
    cols = [part.remote(b.blocks_ref, key, P) for b in bundles]
    reduce = ray_tpu.remote(_map_groups_reduce).options(num_returns=2)
    return [reduce.remote(key, fn, batch_format, *[c[p] if P > 1 else c for c in cols]) for p in range(P)]


def _hash_partition_rows(blocks: list[Block], key: str, P: int):
    t = BlockAccessor.concat(blocks)
    acc = BlockAccessor.for_block(t)
    if acc.num_rows() == 0:
        empty = acc.to_arrow()
        return tuple(empty for _ in range(P)) if P > 1 else empty
    col = acc.to_numpy_batch()[key]
    assign = np.asarray([_stable_hash(v.item() if hasattr(v, "item") else v) % P for v in col])
    parts = [acc.take_indices(np.nonzero(assign == p)[0]) for p in range(P)]
    return tuple(parts) if P > 1 else parts[0]


def _map_groups_reduce(key, fn, batch_format, *parts):
    t = BlockAccessor.concat(list(parts))
    acc = BlockAccessor.for_block(t)
    out_blocks: list = []
    if acc.num_rows():
        batch = acc.to_numpy_batch()
        col = batch[key]
        uniq = sorted({v.item() if hasattr(v, "item") else v for v in col})
        for gk in uniq:
            idx = np.nonzero(col == gk)[0]
            sub_block = acc.take_indices(idx)
            sub_acc = BlockAccessor.for_block(sub_block)
            group = sub_acc.to_pandas() if batch_format == "pandas" else sub_acc.to_numpy_batch()
            out = fn(group)
            out_blocks.append(BlockAccessor.batch_to_block(out))
    block = BlockAccessor.concat(out_blocks)
    return [block], [BlockAccessor.for_block(block).get_metadata()]
