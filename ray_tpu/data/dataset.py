"""Dataset: the lazy, distributed data-frame of ray_tpu.data.

Reference: ``python/ray/data/dataset.py`` (transformations build a logical
plan; consumption triggers the streaming executor), ``grouped_data.py``
(GroupedData), ``dataset.py:1161`` (streaming_split). All transformations
are lazy and fused where legal; consumption streams bundles out of the
executor without materializing the whole dataset in the driver.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Iterator, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data import plan as L
from ray_tpu.data.aggregate import AbsMax, AggregateFn, Count, Max, Mean, Min, Std, Sum
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.datasource import write_block
from ray_tpu.data.execution import RefBundle, StreamingExecutor, execute_to_bundles
from ray_tpu.data.iterator import DataIterator, SplitCoordinator, SplitIterator


class Dataset:
    def __init__(self, plan: L.LogicalPlan):
        self._plan = plan

    # -- transformations (lazy) ---------------------------------------------

    def _with(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(self._plan.with_op(op))

    def map(self, fn, *, fn_args=(), fn_kwargs=None, num_cpus=None, concurrency=None, compute=None, fn_constructor_args=()) -> "Dataset":
        return self._with(L.MapRows(fn=fn, fn_args=tuple(fn_args), fn_kwargs=fn_kwargs or {}, num_cpus=num_cpus, concurrency=concurrency, compute=compute, fn_constructor_args=tuple(fn_constructor_args)))

    def map_batches(
        self,
        fn,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        fn_args=(),
        fn_kwargs=None,
        fn_constructor_args=(),
        fn_constructor_kwargs=None,
        num_cpus=None,
        num_tpus=None,
        compute=None,
        concurrency=None,
        zero_copy_batch: bool = False,
    ) -> "Dataset":
        return self._with(
            L.MapBatches(
                fn=fn,
                fn_args=tuple(fn_args),
                fn_kwargs=fn_kwargs or {},
                fn_constructor_args=tuple(fn_constructor_args),
                fn_constructor_kwargs=fn_constructor_kwargs or {},
                num_cpus=num_cpus,
                num_tpus=num_tpus,
                compute=compute,
                concurrency=concurrency,
                batch_size=batch_size,
                batch_format=batch_format,
                zero_copy_batch=zero_copy_batch,
            )
        )

    def flat_map(self, fn, **kwargs) -> "Dataset":
        return self._with(L.FlatMap(fn=fn, **_map_opts(kwargs)))

    def filter(self, fn, **kwargs) -> "Dataset":
        return self._with(L.Filter(fn=fn, **_map_opts(kwargs)))

    def add_column(self, name: str, fn: Callable[[dict], Any]) -> "Dataset":
        def add(batch, _name=name, _fn=fn):
            batch[_name] = np.asarray(_fn(batch))
            return batch

        return self._with(L.MapBatches(fn=add, batch_format="numpy"))

    def drop_columns(self, cols: list[str]) -> "Dataset":
        def drop(batch, _cols=tuple(cols)):
            return {k: v for k, v in batch.items() if k not in _cols}

        return self._with(L.MapBatches(fn=drop, batch_format="numpy"))

    def select_columns(self, cols: list[str]) -> "Dataset":
        def select(batch, _cols=tuple(cols)):
            missing = [c for c in _cols if c not in batch]
            if missing:
                raise KeyError(f"Columns not found: {missing}")
            return {k: batch[k] for k in _cols}

        return self._with(L.MapBatches(fn=select, batch_format="numpy"))

    def rename_columns(self, mapping: dict[str, str]) -> "Dataset":
        def rename(batch, _m=tuple(mapping.items())):
            m = dict(_m)
            return {m.get(k, k): v for k, v in batch.items()}

        return self._with(L.MapBatches(fn=rename, batch_format="numpy"))

    def random_sample(self, fraction: float, *, seed: Optional[int] = None) -> "Dataset":
        def sample(batch, _f=fraction, _seed=seed):
            n = len(next(iter(batch.values()))) if batch else 0
            if _seed is None:
                rng = np.random.default_rng()
            else:
                # Decorrelate blocks: an identically-seeded rng per block
                # would pick the SAME row positions in every block. Mix the
                # seed with a content fingerprint (deterministic across runs
                # and across worker processes).
                import zlib

                first = next(iter(batch.values()))
                try:
                    fp = zlib.crc32(np.ascontiguousarray(first).tobytes())
                except (TypeError, ValueError):
                    fp = zlib.crc32(repr(first[:8].tolist()).encode())
                rng = np.random.default_rng([_seed, fp, n])
            mask = rng.random(n) < _f
            return {k: v[mask] for k, v in batch.items()}

        return self._with(L.MapBatches(fn=sample, batch_format="numpy"))

    def limit(self, n: int) -> "Dataset":
        return self._with(L.Limit(limit=n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(L.AllToAll(kind="repartition", options={"num_blocks": num_blocks}))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(L.AllToAll(kind="random_shuffle", options={"seed": seed}))

    def sort(self, key: Union[str, list[str]], descending: bool = False) -> "Dataset":
        return self._with(L.AllToAll(kind="sort", options={"key": key, "descending": descending}))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(L.Union(others=[o._plan for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(L.Zip(other=other._plan))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # -- global aggregations -------------------------------------------------

    def aggregate(self, *aggs: AggregateFn) -> dict:
        ds = self._with(L.AllToAll(kind="aggregate", options={"key": None, "aggs": list(aggs)}))
        rows = ds.take_all()
        return rows[0] if rows else {}

    def sum(self, on: str):
        return self.aggregate(Sum(on)).get(f"sum({on})")

    def min(self, on: str):
        return self.aggregate(Min(on)).get(f"min({on})")

    def max(self, on: str):
        return self.aggregate(Max(on)).get(f"max({on})")

    def mean(self, on: str):
        return self.aggregate(Mean(on)).get(f"mean({on})")

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(Std(on, ddof)).get(f"std({on})")

    def unique(self, column: str) -> list:
        rows = self.groupby(column).count().take_all()
        return sorted(r[column] for r in rows)

    # -- consumption ---------------------------------------------------------

    def iter_bundles(self) -> Iterator[RefBundle]:
        yield from StreamingExecutor(self._plan.copy())

    def _iterator_source(self):
        for bundle in self.iter_bundles():
            yield bundle.blocks_ref

    def iterator(self) -> DataIterator:
        return DataIterator(self._iterator_source)

    def iter_rows(self) -> Iterator[dict]:
        return self.iterator().iter_rows()

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        return self.iterator().iter_batches(**kwargs)

    def iter_jax_batches(self, **kwargs) -> Iterator[dict]:
        return self.iterator().iter_jax_batches(**kwargs)

    def iter_torch_batches(self, **kwargs) -> Iterator[dict]:
        return self.iterator().iter_torch_batches(**kwargs)

    def take(self, limit: int = 20) -> list[dict]:
        out = []
        for row in self.limit(limit).iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def take_all(self) -> list[dict]:
        return list(self.iter_rows())

    def take_batch(self, batch_size: int = 20, *, batch_format: str = "numpy"):
        for batch in self.limit(batch_size).iter_batches(
            batch_size=batch_size, batch_format=batch_format, prefetch_batches=0
        ):
            return batch
        return {}

    def count(self) -> int:
        # Metadata-only when possible: sum bundle row counts, no block fetch.
        return sum(b.num_rows for b in self.iter_bundles())

    def schema(self):
        for bundle in self.iter_bundles():
            for m in bundle.metas:
                if m.schema is not None:
                    return m.schema
        return None

    def columns(self) -> Optional[list[str]]:
        s = self.schema()
        return list(s.names) if s is not None else None

    def num_blocks(self) -> int:
        return sum(len(b.metas) for b in self.iter_bundles())

    def size_bytes(self) -> int:
        return sum(b.size_bytes for b in self.iter_bundles())

    def stats(self) -> str:
        bundles = self.materialize()._bundles
        rows = sum(b.num_rows for b in bundles)
        return (
            f"Dataset(plan={self._plan!r}, blocks={sum(len(b.metas) for b in bundles)}, "
            f"rows={rows}, bytes={sum(b.size_bytes for b in bundles)})"
        )

    def to_pandas(self, limit: Optional[int] = None):
        import pandas as pd

        ds = self.limit(limit) if limit is not None else self
        frames = []
        for bundle in ds.iter_bundles():
            for block in ray_tpu.get(bundle.blocks_ref):
                frames.append(BlockAccessor.for_block(block).to_pandas())
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def to_arrow_refs(self) -> list:
        return [b.blocks_ref for b in self.iter_bundles()]

    def materialize(self) -> "MaterializedDataset":
        return MaterializedDataset(list(self.iter_bundles()))

    # -- splits --------------------------------------------------------------

    def split(self, n: int, *, equal: bool = False) -> list["MaterializedDataset"]:
        bundles = list(self.iter_bundles())
        if equal:
            total = sum(b.num_rows for b in bundles)
            per = total // n
            return _split_by_rows(bundles, [per] * n)
        parts: list[list[RefBundle]] = [[] for _ in range(n)]
        for i, b in enumerate(bundles):
            parts[i % n].append(b)
        return [MaterializedDataset(p) for p in parts]

    def train_test_split(self, test_size: float, *, shuffle: bool = False, seed=None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        bundles = list(ds.iter_bundles())
        total = sum(b.num_rows for b in bundles)
        n_test = int(total * test_size) if isinstance(test_size, float) else int(test_size)
        train, test = _split_by_rows(bundles, [total - n_test, n_test])
        return train, test

    def streaming_split(
        self, n: int, *, equal: bool = True, locality_hints=None
    ) -> list[DataIterator]:
        """N coordinated streaming iterators over ONE execution per epoch
        (reference: ``dataset.py:1161``). Safe to consume from n train
        workers concurrently."""
        coord_cls = ray_tpu.remote(SplitCoordinator)
        coord = coord_cls.options(max_concurrency=max(n + 1, 2)).remote(
            self._plan.copy(), n, equal
        )
        return [SplitIterator(coord, i) for i in range(n)]

    # -- writes --------------------------------------------------------------

    def _write(self, path: str, file_format: str, **kwargs) -> list[str]:
        results = []
        remote = ray_tpu.remote(_write_bundle)
        for i, bundle in enumerate(self.iter_bundles()):
            results.append(remote.remote(bundle.blocks_ref, path, file_format, i, kwargs))
        return [p for ps in ray_tpu.get(results) for p in ps]

    def write_parquet(self, path: str, **kwargs):
        return self._write(path, "parquet", **kwargs)

    def write_csv(self, path: str, **kwargs):
        return self._write(path, "csv", **kwargs)

    def write_json(self, path: str, **kwargs):
        return self._write(path, "json", **kwargs)

    def write_numpy(self, path: str, *, column: Optional[str] = None, **kwargs):
        ds = self.select_columns([column]) if column is not None else self
        return ds._write(path, "npy", **kwargs)

    def __repr__(self):
        return f"Dataset({self._plan!r})"

    schema_repr = __repr__


def _map_opts(kwargs: dict) -> dict:
    out = {}
    for k in ("fn_args", "fn_kwargs", "num_cpus", "concurrency", "compute", "fn_constructor_args", "fn_constructor_kwargs"):
        if k in kwargs and kwargs[k] is not None:
            out[k] = kwargs[k]
    if "fn_args" in out:
        out["fn_args"] = tuple(out["fn_args"])
    return out


def _write_bundle(blocks: list[Block], path: str, file_format: str, index: int, kwargs: dict):
    out = []
    for j, b in enumerate(blocks):
        if BlockAccessor.for_block(b).num_rows():
            out.append(write_block(b, path, file_format, index * 10000 + j, **kwargs))
    return out


def _slice_bundle_rows(bundles: list[RefBundle], start: int, end: int) -> list[RefBundle]:
    """Driver-side row-range selection over materialized bundles."""
    refs = [b.blocks_ref for b in bundles]
    offsets = np.cumsum([0] + [b.num_rows for b in bundles])
    sel = [
        (refs[j], int(offsets[j]))
        for j in range(len(bundles))
        if offsets[j + 1] > start and offsets[j] < end
    ]
    if not sel:
        return []
    base = sel[0][1]
    from ray_tpu.data.exchange import _repartition_reduce

    blocks_ref, meta_ref = (
        ray_tpu.remote(_repartition_reduce)
        .options(num_returns=2)
        .remote(start - base, end - base, *[r for r, _ in sel])
    )
    return [RefBundle(blocks_ref, ray_tpu.get(meta_ref))]


def _split_by_rows(bundles: list[RefBundle], sizes: list[int]) -> list["MaterializedDataset"]:
    out = []
    start = 0
    for s in sizes:
        out.append(MaterializedDataset(_slice_bundle_rows(bundles, start, start + s)))
        start += s
    return out


def _bundles_from_blocks(blocks: list[Block]) -> list[RefBundle]:
    bundles = []
    for b in blocks:
        meta = BlockAccessor.for_block(b).get_metadata()
        bundles.append(RefBundle(ray_tpu.put([b]), [meta]))
    return bundles


class MaterializedDataset(Dataset):
    """A Dataset whose blocks are resident in the object store.

    Reference: ``MaterializedDataset`` in ``dataset.py`` — re-iteration does
    not re-execute the plan.
    """

    def __init__(self, bundles: list[RefBundle]):
        self._bundles = bundles
        super().__init__(L.LogicalPlan([L.InputData(bundles=bundles)]))

    def iter_bundles(self) -> Iterator[RefBundle]:
        yield from self._bundles

    def materialize(self) -> "MaterializedDataset":
        return self


class GroupedData:
    """Reference: ``python/ray/data/grouped_data.py``."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return self._ds._with(
            L.AllToAll(kind="aggregate", options={"key": self._key, "aggs": list(aggs)})
        )

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on: str, ddof: int = 1) -> Dataset:
        return self.aggregate(Std(on, ddof))

    def map_groups(self, fn, *, batch_format: str = "numpy") -> Dataset:
        return self._ds._with(
            L.AllToAll(
                kind="map_groups",
                options={"key": self._key, "fn": fn, "batch_format": batch_format},
            )
        )
