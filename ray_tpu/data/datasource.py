"""Datasources: pluggable readers/writers producing blocks.

Reference: ``python/ray/data/datasource/`` (Datasource ABC + ReadTask;
parquet/csv/json/images/binary/range readers, write API). A ``ReadTask`` is
a zero-arg callable returning an iterator of blocks; the executor runs each
as a remote task, so reads parallelize across the cluster exactly like the
reference's.
"""

from __future__ import annotations

import glob as _glob
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata, TENSOR_COLUMN


@dataclass
class ReadTask:
    """One parallel unit of reading. ``fn`` runs inside a remote task."""

    fn: Callable[[], Iterable[Block]]
    metadata: BlockMetadata

    def __call__(self) -> Iterable[Block]:
        return self.fn()


class Datasource:
    """Reference: ``python/ray/data/datasource/datasource.py``."""

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Datasource", "")


class RangeDatasource(Datasource):
    def __init__(self, n: int, use_tensor: bool = False, tensor_shape: tuple = ()):
        self._n = n
        self._use_tensor = use_tensor
        self._tensor_shape = tensor_shape

    def estimate_inmemory_data_size(self):
        return self._n * 8 * max(1, int(np.prod(self._tensor_shape or (1,))))

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        parallelism = max(1, min(parallelism, self._n or 1))
        tasks = []
        per = -(-self._n // parallelism) if self._n else 0
        for i in range(parallelism):
            start, end = i * per, min((i + 1) * per, self._n)
            if start >= end and self._n:
                break
            use_tensor, shape = self._use_tensor, self._tensor_shape

            def fn(start=start, end=end):
                ids = np.arange(start, end, dtype=np.int64)
                if use_tensor:
                    data = np.broadcast_to(
                        ids.reshape((-1,) + (1,) * len(shape)), (len(ids),) + shape
                    ).copy()
                    yield BlockAccessor.batch_to_block({"data": data})
                else:
                    yield BlockAccessor.batch_to_block({"id": ids})

            meta = BlockMetadata(num_rows=end - start, size_bytes=(end - start) * 8)
            tasks.append(ReadTask(fn, meta))
        return tasks or [ReadTask(lambda: iter(()), BlockMetadata(0, 0))]


class ItemsDatasource(Datasource):
    def __init__(self, items: list):
        self._items = items

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        items = self._items
        n = len(items)
        parallelism = max(1, min(parallelism, n or 1))
        per = -(-n // parallelism) if n else 0
        tasks = []
        for i in range(parallelism):
            chunk = items[i * per : (i + 1) * per]
            if not chunk and n:
                break

            def fn(chunk=chunk):
                if chunk and isinstance(chunk[0], dict):
                    yield BlockAccessor.rows_to_block(chunk)
                else:
                    yield BlockAccessor.rows_to_block([{"item": x} for x in chunk])

            tasks.append(ReadTask(fn, BlockMetadata(num_rows=len(chunk), size_bytes=0)))
        return tasks or [ReadTask(lambda: iter(()), BlockMetadata(0, 0))]


class BlocksDatasource(Datasource):
    """Wraps already-materialized blocks (from_numpy/from_pandas/from_arrow)."""

    def __init__(self, blocks: list[Block]):
        self._blocks = blocks

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        tasks = []
        for b in self._blocks:
            acc = BlockAccessor.for_block(b)
            tasks.append(ReadTask(lambda b=b: [BlockAccessor.batch_to_block(b)], acc.get_metadata()))
        return tasks


# -- file-based sources ------------------------------------------------------


def _expand_paths(paths) -> list[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs.sort()  # deterministic traversal order across filesystems
                out.extend(os.path.join(root, f) for f in sorted(files) if not f.startswith("."))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"No input files found for {paths!r}")
    return out


@dataclass
class FileBasedDatasource(Datasource):
    """Reference: ``python/ray/data/datasource/file_based_datasource.py``."""

    paths: Any
    read_kwargs: dict = field(default_factory=dict)

    def __post_init__(self):
        self._files = _expand_paths(self.paths)

    def _read_file(self, path: str) -> Iterator[Block]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self):
        try:
            return sum(os.path.getsize(f) for f in self._files)
        except OSError:
            return None

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        files = self._files
        parallelism = max(1, min(parallelism, len(files)))
        per = -(-len(files) // parallelism)
        tasks = []
        for i in range(parallelism):
            chunk = files[i * per : (i + 1) * per]
            if not chunk:
                break

            def fn(chunk=chunk, self=self):
                for path in chunk:
                    yield from self._read_file(path)

            size = sum(os.path.getsize(f) for f in chunk if os.path.exists(f))
            tasks.append(
                ReadTask(fn, BlockMetadata(num_rows=0, size_bytes=size, input_files=chunk))
            )
        return tasks


class ParquetDatasource(FileBasedDatasource):
    def _read_file(self, path):
        import pyarrow.parquet as pq

        columns = self.read_kwargs.get("columns")
        f = pq.ParquetFile(path)
        for rg in range(f.num_row_groups):
            yield f.read_row_group(rg, columns=columns)


class CSVDatasource(FileBasedDatasource):
    def _read_file(self, path):
        from pyarrow import csv

        yield csv.read_csv(path, **self.read_kwargs)


class JSONDatasource(FileBasedDatasource):
    """Newline-delimited JSON (and plain JSON arrays as fallback)."""

    def _read_file(self, path):
        import json as _json

        from pyarrow import json as pj

        try:
            yield pj.read_json(path, **self.read_kwargs)
        except Exception:
            with open(path) as f:
                data = _json.load(f)
            if isinstance(data, dict):
                data = [data]
            yield BlockAccessor.rows_to_block(data)


class TextDatasource(FileBasedDatasource):
    def _read_file(self, path):
        drop_empty = self.read_kwargs.get("drop_empty_lines", True)
        with open(path, encoding=self.read_kwargs.get("encoding", "utf-8")) as f:
            lines = [ln.rstrip("\n") for ln in f]
        if drop_empty:
            lines = [ln for ln in lines if ln]
        yield BlockAccessor.batch_to_block({"text": np.asarray(lines, dtype=object)})


class BinaryDatasource(FileBasedDatasource):
    def _read_file(self, path):
        with open(path, "rb") as f:
            data = f.read()
        cols = {"bytes": np.asarray([data], dtype=object)}
        if self.read_kwargs.get("include_paths"):
            cols["path"] = np.asarray([path], dtype=object)
        yield BlockAccessor.batch_to_block(cols)


class ImageDatasource(FileBasedDatasource):
    """Decodes images into a fixed-shape tensor column (HWC uint8/float32)."""

    def _read_file(self, path):
        from PIL import Image

        size = self.read_kwargs.get("size")
        mode = self.read_kwargs.get("mode", "RGB")
        img = Image.open(path).convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        cols = {"image": arr[None]}
        if self.read_kwargs.get("include_paths"):
            cols["path"] = np.asarray([path], dtype=object)
        yield BlockAccessor.batch_to_block(cols)


class NumpyDatasource(FileBasedDatasource):
    def _read_file(self, path):
        arr = np.load(path, allow_pickle=False)
        yield BlockAccessor.batch_to_block({self.read_kwargs.get("column", TENSOR_COLUMN): arr})


class TFRecordsDatasource(FileBasedDatasource):
    """Minimal TFRecord reader (uncompressed) → tf.train.Example features.

    Pure-python record framing (length/crc framing per the TFRecord spec);
    requires no tensorflow. Feature decode supports bytes/float/int64 lists.
    """

    def _read_file(self, path):
        rows = []
        for rec in _iter_tfrecords(path):
            rows.append(_parse_tf_example(rec))
        if rows:
            yield BlockAccessor.rows_to_block(rows)


def _iter_tfrecords(path: str) -> Iterator[bytes]:
    import struct

    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            (length,) = struct.unpack("<Q", header)
            f.read(4)  # length crc
            data = f.read(length)
            f.read(4)  # data crc
            yield data


def _parse_tf_example(data: bytes) -> dict:
    """Hand-rolled protobuf walk of tf.train.Example (features map)."""
    out: dict[str, Any] = {}
    feats = _pb_find(data, 1)
    for item in _pb_repeated(feats, 1):
        key = _pb_find(item, 1).decode()
        feature = _pb_find(item, 2)
        for tag in (1, 2, 3):  # bytes_list / float_list / int64_list
            lst = _pb_find(feature, tag)
            if lst is not None:
                vals = _pb_list_values(lst, tag)
                out[key] = vals[0] if len(vals) == 1 else vals
                break
    return out


def _signed64(x: int) -> int:
    # Protobuf varints carry int64 as two's complement in 64 bits.
    return x - (1 << 64) if x >= (1 << 63) else x


def _pb_varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = val = 0
    while True:
        b = buf[i]
        val |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            return val, i
        shift += 7


def _pb_walk(buf: bytes):
    i = 0
    while i < len(buf):
        key, i = _pb_varint(buf, i)
        tag, wire = key >> 3, key & 7
        if wire == 0:
            val, i = _pb_varint(buf, i)
        elif wire == 2:
            ln, i = _pb_varint(buf, i)
            val = buf[i : i + ln]
            i += ln
        elif wire == 5:
            val = buf[i : i + 4]
            i += 4
        elif wire == 1:
            val = buf[i : i + 8]
            i += 8
        else:
            raise ValueError(f"bad wire type {wire}")
        yield tag, wire, val


def _pb_find(buf: bytes, tag: int):
    if buf is None:
        return None
    for t, _, v in _pb_walk(buf):
        if t == tag:
            return v
    return None


def _pb_repeated(buf: bytes, tag: int):
    if buf is None:
        return
    for t, _, v in _pb_walk(buf):
        if t == tag:
            yield v


def _pb_list_values(buf: bytes, kind: int) -> list:
    import struct

    vals: list = []
    for t, wire, v in _pb_walk(buf):
        if t != 1:
            continue
        if kind == 1:
            vals.append(v)
        elif kind == 2:
            if wire == 2:  # packed floats
                vals.extend(struct.unpack(f"<{len(v)//4}f", v))
            else:
                vals.append(struct.unpack("<f", v)[0])
        else:
            if wire == 2:  # packed varints
                i = 0
                while i < len(v):
                    x, i = _pb_varint(v, i)
                    vals.append(_signed64(x))
            else:
                vals.append(_signed64(v))
    return vals


# -- write side --------------------------------------------------------------


def write_block(block: Block, path: str, file_format: str, index: int, **kwargs) -> str:
    os.makedirs(path, exist_ok=True)
    t = BlockAccessor.for_block(block).to_arrow()
    out = os.path.join(path, f"part-{index:06d}.{file_format}")
    if file_format == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(t, out, **kwargs)
    elif file_format == "csv":
        from pyarrow import csv

        csv.write_csv(t, out)
    elif file_format == "json":
        import json

        with open(out, "w") as f:
            for row in BlockAccessor.for_block(block).iter_rows():
                f.write(json.dumps({k: _json_safe(v) for k, v in row.items()}) + "\n")
    elif file_format == "npy":
        batch = BlockAccessor.for_block(block).to_numpy_batch()
        if len(batch) != 1:
            raise ValueError("write_numpy requires a single-column dataset")
        np.save(out, next(iter(batch.values())))
    else:
        raise ValueError(f"Unsupported format {file_format}")
    return out


def _json_safe(v):
    if isinstance(v, (np.ndarray,)):
        return v.tolist()
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, np.generic):
        return v.item()
    return v


class WebDatasetDatasource(FileBasedDatasource):
    """WebDataset tar shards (reference: ``datasource/webdataset_datasource.py``).

    Each sample = consecutive tar members sharing a basename; member
    extensions become columns (``jpg``/``png`` decode to image tensors when
    PIL is available and ``decode=True``, ``json`` parses, ``cls``/``txt``
    decode to scalars, everything else stays bytes). Pure tarfile — no
    webdataset dependency.
    """

    def _read_file(self, path):
        import json as _json
        import tarfile

        decode = self.read_kwargs.get("decode", True)
        rows: list[dict] = []
        with tarfile.open(path) as tf:
            current_key = None
            sample: dict = {}
            for member in tf:
                if not member.isfile():
                    continue
                name = member.name
                base, dot, ext = name.partition(".")
                if current_key is not None and base != current_key and sample:
                    rows.append(sample)
                    sample = {}
                current_key = base
                data = tf.extractfile(member).read()
                if decode:
                    if ext in ("txt", "text"):
                        data = data.decode()
                    elif ext in ("cls", "id", "index"):
                        data = int(data)
                    elif ext == "json":
                        data = _json.loads(data)
                    elif ext in ("jpg", "jpeg", "png") :
                        try:
                            import io as _io

                            from PIL import Image

                            data = np.asarray(Image.open(_io.BytesIO(data)))
                        except ImportError:
                            pass  # leave raw bytes
                sample["__key__"] = base
                sample[ext] = data
            if sample:
                rows.append(sample)
        if rows:
            yield BlockAccessor.rows_to_block(rows)


class MongoDatasource(Datasource):
    """MongoDB collections (reference: ``datasource/mongo_datasource.py``).
    Requires ``pymongo`` (not bundled — gated import)."""

    def __init__(self, uri: str, database: str, collection: str, pipeline=None):
        try:
            import pymongo  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "read_mongo requires pymongo, which is not installed in this "
                "environment"
            ) from e
        self.uri, self.database, self.collection = uri, database, collection
        self.pipeline = pipeline or []

    def estimate_inmemory_data_size(self):
        return None

    def get_read_tasks(self, parallelism: int) -> list:
        uri, db, coll, pipe = self.uri, self.database, self.collection, self.pipeline

        def fn():
            import pymongo

            client = pymongo.MongoClient(uri)
            docs = list(client[db][coll].aggregate(pipe) if pipe else client[db][coll].find())
            for d in docs:
                d.pop("_id", None)
            if docs:
                yield BlockAccessor.rows_to_block(docs)

        return [ReadTask(fn, BlockMetadata(num_rows=0, size_bytes=None, input_files=[]))]


class BigQueryDatasource(Datasource):
    """BigQuery tables/queries (reference: ``datasource/bigquery_datasource.py``).
    Requires ``google-cloud-bigquery`` (gated import)."""

    def __init__(self, project_id: str, query: Optional[str] = None, dataset: Optional[str] = None):
        try:
            from google.cloud import bigquery  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "read_bigquery requires google-cloud-bigquery, which is not "
                "installed in this environment"
            ) from e
        if not (query or dataset):
            raise ValueError(
                "read_bigquery needs query=... or dataset=... "
                "(dataset must be a fully-qualified table id: "
                "'project.dataset.table')"
            )
        self.project_id, self.query, self.dataset = project_id, query, dataset

    def estimate_inmemory_data_size(self):
        return None

    def get_read_tasks(self, parallelism: int) -> list:
        project, query, dataset = self.project_id, self.query, self.dataset

        def fn():
            from google.cloud import bigquery

            client = bigquery.Client(project=project)
            if query:
                table = client.query(query).to_arrow()
            else:
                table = client.list_rows(dataset).to_arrow()
            yield table

        return [ReadTask(fn, BlockMetadata(num_rows=0, size_bytes=None, input_files=[]))]


class SQLDatasource(Datasource):
    """Rows from a SQL query via a DB-API connection factory.

    Reference: ``python/ray/data/datasource/sql_datasource.py`` (``read_sql``
    takes a query + zero-arg connection factory; works with sqlite3,
    psycopg2, mysql-connector — anything DB-API 2.0). Parallelism: the query
    runs once per read task with LIMIT/OFFSET windows when ``parallelism > 1``
    (like the reference's sharded reads); drivers without cheap OFFSET can
    pass ``parallelism=1``.
    """

    def __init__(
        self,
        sql: str,
        connection_factory,
        parallelism_hint: int = 1,
        order_by: Optional[str] = None,
    ):
        self._sql = sql
        self._factory = connection_factory
        self._hint = parallelism_hint
        self._order_by = order_by
        if parallelism_hint > 1 and not order_by:
            # LIMIT/OFFSET windows over an UNORDERED query re-executed per
            # task are not disjoint on engines with nondeterministic scan
            # order (observed on PostgreSQL parallel seq scans) — rows would
            # silently duplicate/vanish. Force the caller to choose the key.
            raise ValueError(
                "read_sql with parallelism > 1 needs order_by= (a column list "
                "giving a deterministic total order) so OFFSET windows are "
                "disjoint across read tasks"
            )

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        sql, factory, order_by = self._sql, self._factory, self._order_by
        parallelism = max(1, min(parallelism, self._hint))

        def run_query(window=None):
            def fn():
                conn = factory()
                try:
                    cur = conn.cursor()
                    if window is None:
                        q = sql
                    else:
                        q = (
                            f"SELECT * FROM ({sql}) AS _t ORDER BY {order_by} "
                            f"LIMIT {window[1]} OFFSET {window[0]}"
                        )
                    cur.execute(q)
                    cols = [d[0] for d in cur.description]
                    rows = cur.fetchall()
                    if rows:
                        data = {c: np.asarray([r[i] for r in rows]) for i, c in enumerate(cols)}
                        yield BlockAccessor.batch_to_block(data)
                finally:
                    conn.close()

            return fn

        if parallelism == 1:
            return [ReadTask(run_query(), BlockMetadata(None, None))]
        # window the query; an extra tail task catches the remainder
        conn = factory()
        try:
            cur = conn.cursor()
            cur.execute(f"SELECT COUNT(*) FROM ({sql}) AS _t")
            total = int(cur.fetchone()[0])
        finally:
            conn.close()
        per = -(-total // parallelism)
        tasks = []
        for i in range(parallelism):
            start = i * per
            if start >= total:
                break
            tasks.append(
                ReadTask(run_query((start, per)), BlockMetadata(min(per, total - start), None))
            )
        return tasks or [ReadTask(run_query(), BlockMetadata(0, 0))]
