"""Block layer: the unit of data movement in ray_tpu.data.

Reference: ``python/ray/data/block.py`` (Block = Arrow table / pandas frame,
``BlockAccessor`` dispatch, ``BlockMetadata``). Here the canonical block is a
``pyarrow.Table``; accessors also understand dict-of-numpy ("numpy batch")
and ``pandas.DataFrame`` so user ``map_batches`` fns can return any of the
three. TPU-first consequence: ``to_numpy_batch`` produces contiguous
fixed-dtype column arrays ready for ``jax.device_put`` with no further
copies; fixed-shape tensor columns are stored as Arrow FixedSizeList with
the shape in schema metadata (the counterpart of the reference's
ArrowTensorArray extension type).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import Any, Iterable, Iterator, Optional, Union

import numpy as np

TENSOR_COLUMN = "__value__"  # single-column wrapper for bare ndarrays


def _pa():
    import pyarrow

    return pyarrow


Block = Any  # pyarrow.Table at rest; pandas/numpy-dict accepted in flight
NumpyBatch = dict  # str -> np.ndarray


@dataclass
class BlockMetadata:
    """Sidecar stats carried with every block ref through the plan.

    Reference: ``python/ray/data/block.py`` BlockMetadata (num_rows,
    size_bytes, schema, input_files).
    """

    num_rows: int
    size_bytes: int
    schema: Optional[Any] = None
    input_files: Optional[list[str]] = None


class BlockAccessor:
    """Uniform view over arrow / pandas / numpy-dict blocks."""

    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # -- construction -------------------------------------------------------

    @staticmethod
    def batch_to_block(batch: Union[Block, NumpyBatch, np.ndarray]) -> Block:
        """Normalize any map_batches return value to an arrow table."""
        pa = _pa()
        if isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, np.ndarray):
            batch = {TENSOR_COLUMN: batch}
        if isinstance(batch, dict):
            cols, names = [], []
            n = None
            for k, v in batch.items():
                v = np.asarray(v)
                if n is None:
                    n = len(v)
                elif len(v) != n:
                    raise ValueError(
                        f"Batch columns have unequal lengths: {k} has {len(v)}, expected {n}"
                    )
                names.append(k)
                cols.append(v)
            return _table_from_numpy_columns(cols, names)
        try:
            import pandas as pd

            if isinstance(batch, pd.DataFrame):
                return pa.Table.from_pandas(batch, preserve_index=False)
        except ImportError:
            pass
        raise TypeError(f"Cannot convert {type(batch)} to a block")

    @staticmethod
    def rows_to_block(rows: Iterable[dict]) -> Block:
        rows = list(rows)
        if not rows:
            return _pa().table({})
        if not isinstance(rows[0], dict):
            rows = [{TENSOR_COLUMN: r} for r in rows]
        cols: dict[str, list] = {k: [] for k in rows[0]}
        for r in rows:
            if set(r) != set(cols):
                raise ValueError(f"Row schema mismatch: {set(r)} vs {set(cols)}")
            for k, v in r.items():
                cols[k].append(v)
        return BlockAccessor.batch_to_block({k: _stack_values(v) for k, v in cols.items()})

    # -- stats --------------------------------------------------------------

    def num_rows(self) -> int:
        b = self._block
        if isinstance(b, _pa().Table):
            return b.num_rows
        if isinstance(b, dict):
            return len(next(iter(b.values()))) if b else 0
        return len(b)

    def size_bytes(self) -> int:
        b = self._block
        if isinstance(b, _pa().Table):
            return b.nbytes
        if isinstance(b, dict):
            return int(sum(np.asarray(v).nbytes for v in b.values()))
        try:
            return int(b.memory_usage(index=False).sum())
        except Exception:
            return sys.getsizeof(b)

    def schema(self):
        b = self._block
        if isinstance(b, _pa().Table):
            return b.schema
        return BlockAccessor.batch_to_block(b).schema

    def get_metadata(self, input_files: Optional[list[str]] = None) -> BlockMetadata:
        return BlockMetadata(
            num_rows=self.num_rows(),
            size_bytes=self.size_bytes(),
            schema=self.schema() if self.num_rows() else None,
            input_files=input_files,
        )

    # -- conversion ---------------------------------------------------------

    def to_arrow(self):
        return BlockAccessor.batch_to_block(self._block)

    def to_numpy_batch(self) -> NumpyBatch:
        t = self.to_arrow()
        return {name: _arrow_col_to_numpy(t, name) for name in t.column_names}

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def iter_rows(self) -> Iterator[dict]:
        batch = self.to_numpy_batch()
        keys = list(batch)
        for i in range(self.num_rows()):
            yield {k: _unbox(batch[k][i]) for k in keys}

    # -- ops ----------------------------------------------------------------

    def slice(self, start: int, end: int) -> Block:
        return self.to_arrow().slice(start, end - start)

    def take_indices(self, idx: np.ndarray) -> Block:
        return self.to_arrow().take(_pa().array(idx))

    @staticmethod
    def concat(blocks: list[Block]) -> Block:
        pa = _pa()
        tables = [BlockAccessor(b).to_arrow() for b in blocks if BlockAccessor(b).num_rows()]
        if not tables:
            return pa.table({})
        if len(tables) == 1:
            return tables[0]
        meta: dict[bytes, bytes] = {}
        for t in tables:
            meta.update(t.schema.metadata or {})
        out = pa.concat_tables(
            [t.replace_schema_metadata(None) for t in tables], promote_options="default"
        )
        return out.replace_schema_metadata(meta or None)


# -- internals ---------------------------------------------------------------


def _stack_values(vals: list) -> np.ndarray:
    try:
        arr = np.asarray(vals)
        if arr.dtype != object or not (vals and isinstance(vals[0], (list, np.ndarray))):
            return arr
    except Exception:
        pass
    return np.asarray(vals, dtype=object)


def _table_from_numpy_columns(cols: list[np.ndarray], names: list[str]):
    pa = _pa()
    meta: dict[bytes, bytes] = {}
    arrays = []
    for v, name in zip(cols, names):
        if v.ndim > 1 and v.dtype != object:
            # Fixed-shape tensor column → FixedSizeList + shape metadata.
            inner_shape = v.shape[1:]
            size = int(np.prod(inner_shape))
            flat = np.ascontiguousarray(v).reshape(-1)
            arrays.append(pa.FixedSizeListArray.from_arrays(pa.array(flat), size))
            meta[f"tensor_shape:{name}".encode()] = ",".join(map(str, inner_shape)).encode()
        elif v.dtype == object:
            arrays.append(pa.array(v.tolist()))
        else:
            arrays.append(pa.array(v))
    t = pa.Table.from_arrays(arrays, names=names)
    if meta:
        t = t.replace_schema_metadata({**(t.schema.metadata or {}), **meta})
    return t


def _arrow_col_to_numpy(t, name: str) -> np.ndarray:
    pa = _pa()
    col = t.column(name)
    if pa.types.is_fixed_size_list(col.type):
        combined = col.combine_chunks()
        if isinstance(combined, pa.ChunkedArray):
            combined = combined.chunk(0) if combined.num_chunks else pa.array([], col.type)
        values = combined.values.to_numpy(zero_copy_only=False)
        width = col.type.list_size
        arr = values.reshape(-1, width)
        shape = _tensor_shape_from_meta(t, name)
        if shape is not None and int(np.prod(shape)) == width:
            arr = arr.reshape((-1,) + tuple(shape))
        return arr
    try:
        return col.to_numpy(zero_copy_only=False)
    except Exception:
        return np.asarray(col.to_pylist(), dtype=object)


def _tensor_shape_from_meta(t, name: str):
    meta = t.schema.metadata or {}
    key = f"tensor_shape:{name}".encode()
    if key in meta:
        return tuple(int(x) for x in meta[key].decode().split(",") if x)
    return None


def _unbox(x):
    if isinstance(x, np.generic):
        return x.item()
    return x
