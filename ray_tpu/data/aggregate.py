"""Aggregation functions for groupby/global aggregation.

Reference: ``python/ray/data/aggregate.py`` (AggregateFn; Count/Sum/Min/Max/
Mean/Std/AbsMax). Each agg is a (partial, merge, finalize) triple applied to
numpy column batches — map-side partials keep the exchange small.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


class AggregateFn:
    name: str = "agg"

    def partial(self, batch: dict) -> Any:
        raise NotImplementedError

    def merge(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def finalize(self, state: Any) -> Any:
        return state


class Count(AggregateFn):
    def __init__(self):
        self.name = "count()"

    def partial(self, batch):
        return len(next(iter(batch.values()))) if batch else 0

    def merge(self, a, b):
        return a + b


class _ColumnAgg(AggregateFn):
    def __init__(self, on: str):
        self.on = on
        self.name = f"{type(self).__name__.lower()}({on})"


class Sum(_ColumnAgg):
    def partial(self, batch):
        return np.asarray(batch[self.on]).sum()

    def merge(self, a, b):
        return a + b

    def finalize(self, state):
        return state.item() if hasattr(state, "item") else state


class Min(_ColumnAgg):
    def partial(self, batch):
        return np.asarray(batch[self.on]).min()

    def merge(self, a, b):
        return min(a, b)

    def finalize(self, state):
        return state.item() if hasattr(state, "item") else state


class Max(_ColumnAgg):
    def partial(self, batch):
        return np.asarray(batch[self.on]).max()

    def merge(self, a, b):
        return max(a, b)

    def finalize(self, state):
        return state.item() if hasattr(state, "item") else state


class Mean(_ColumnAgg):
    def partial(self, batch):
        v = np.asarray(batch[self.on])
        return (v.sum(), len(v))

    def merge(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def finalize(self, state):
        s, n = state
        out = s / n if n else float("nan")
        return out.item() if hasattr(out, "item") else out


class Std(_ColumnAgg):
    """Parallel Welford merge (matches the reference's chunked Std)."""

    def __init__(self, on: str, ddof: int = 1):
        super().__init__(on)
        self.ddof = ddof
        self.name = f"std({on})"

    def partial(self, batch):
        v = np.asarray(batch[self.on], dtype=np.float64)
        n = len(v)
        mean = v.mean() if n else 0.0
        m2 = ((v - mean) ** 2).sum() if n else 0.0
        return (n, mean, m2)

    def merge(self, a, b):
        na, ma, m2a = a
        nb, mb, m2b = b
        n = na + nb
        if n == 0:
            return (0, 0.0, 0.0)
        delta = mb - ma
        mean = ma + delta * nb / n
        m2 = m2a + m2b + delta * delta * na * nb / n
        return (n, mean, m2)

    def finalize(self, state):
        n, _, m2 = state
        if n - self.ddof <= 0:
            return float("nan")
        return float(np.sqrt(m2 / (n - self.ddof)))


class AbsMax(_ColumnAgg):
    def partial(self, batch):
        v = np.asarray(batch[self.on])
        return np.abs(v).max() if len(v) else 0

    def merge(self, a, b):
        return max(a, b)

    def finalize(self, state):
        return state.item() if hasattr(state, "item") else state
