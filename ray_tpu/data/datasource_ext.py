"""Long-tail datasources (reference: ``python/ray/data/datasource/`` — the
reference ships 30+ sources; this module is the second tranche on top of
``datasource.py``'s core set).

Design: everything rides the same ``Datasource``/``ReadTask`` API the
streaming executor already consumes. Sources whose client libraries are not
bundled in this image take an injectable client/transport (tested with
fakes, usable with the real library), or gate the import with a clear
error, mirroring ``MongoDatasource``. Formats with a stdlib/pyarrow path
(Avro, ORC, Arrow IPC, WAV, XML, Delta logs) are implemented for real —
the Avro object-container reader is hand-rolled (null/deflate codecs) so
``read_avro`` needs no fastavro.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Callable, Iterator, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.datasource import (
    Datasource,
    FileBasedDatasource,
    ParquetDatasource,
    ReadTask,
    SQLDatasource,
)

# ---------------------------------------------------------------------------
# Avro object container files (reference: datasource/avro_datasource.py,
# which wraps fastavro; hand-rolled here — OCF spec: header map, zigzag
# varints, per-block codec, 16-byte sync markers)
# ---------------------------------------------------------------------------


class _AvroReader:
    def __init__(self, data: bytes):
        self.buf = data
        self.pos = 0

    # -- primitives ------------------------------------------------------
    def _byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self._byte()
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    def read_bytes(self) -> bytes:
        n = self.read_long()
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def read_utf8(self) -> str:
        return self.read_bytes().decode("utf-8")

    def read_fixed(self, n: int) -> bytes:
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    # -- schema-driven decode -------------------------------------------
    def decode(self, schema) -> Any:
        if isinstance(schema, list):  # union: long index + value
            return self.decode(schema[self.read_long()])
        if isinstance(schema, str):
            t = schema
        else:
            t = schema["type"]
        if t == "null":
            return None
        if t == "boolean":
            return self._byte() == 1
        if t in ("int", "long"):
            return self.read_long()
        if t == "float":
            (v,) = struct.unpack("<f", self.read_fixed(4))
            return v
        if t == "double":
            (v,) = struct.unpack("<d", self.read_fixed(8))
            return v
        if t == "bytes":
            return self.read_bytes()
        if t == "string":
            return self.read_utf8()
        if t == "record":
            return {f["name"]: self.decode(f["type"]) for f in schema["fields"]}
        if t == "enum":
            return schema["symbols"][self.read_long()]
        if t == "fixed":
            return self.read_fixed(schema["size"])
        if t == "array":
            out = []
            while True:
                n = self.read_long()
                if n == 0:
                    break
                if n < 0:  # block with byte size prefix
                    n = -n
                    self.read_long()
                out.extend(self.decode(schema["items"]) for _ in range(n))
            return out
        if t == "map":
            out = {}
            while True:
                n = self.read_long()
                if n == 0:
                    break
                if n < 0:
                    n = -n
                    self.read_long()
                for _ in range(n):
                    # key must decode BEFORE the value: in `d[k()] = v()`
                    # Python evaluates the RHS first
                    key = self.read_utf8()
                    out[key] = self.decode(schema["values"])
            return out
        raise ValueError(f"unsupported avro type {t!r}")


def iter_avro_records(data: bytes) -> Iterator[dict]:
    """Decode every record of an Avro object-container file."""
    r = _AvroReader(data)
    if r.read_fixed(4) != b"Obj\x01":
        raise ValueError("not an Avro object container file")
    meta: dict[str, bytes] = {}
    while True:
        n = r.read_long()
        if n == 0:
            break
        if n < 0:
            n = -n
            r.read_long()
        for _ in range(n):
            key = r.read_utf8()  # key BEFORE value (RHS evaluates first)
            meta[key] = r.read_bytes()
    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    sync = r.read_fixed(16)
    while r.pos < len(r.buf):
        count = r.read_long()
        size = r.read_long()
        payload = r.read_fixed(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        block = _AvroReader(payload)
        for _ in range(count):
            yield block.decode(schema)
        if r.read_fixed(16) != sync:
            raise ValueError("avro sync marker mismatch (corrupt file)")


class AvroDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        with open(path, "rb") as f:
            rows = list(iter_avro_records(f.read()))
        if rows:
            yield BlockAccessor.rows_to_block(rows)


# ---------------------------------------------------------------------------
# ORC + Arrow IPC / Feather (reference: datasource/orc via pyarrow in spirit;
# pyarrow ships both readers)
# ---------------------------------------------------------------------------


class ORCDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        from pyarrow import orc

        yield orc.read_table(path, columns=self.read_kwargs.get("columns"))


class ArrowIPCDatasource(FileBasedDatasource):
    """Arrow IPC files (a.k.a. Feather v2) and stream format."""

    def _read_file(self, path: str) -> Iterator[Block]:
        import pyarrow as pa

        with open(path, "rb") as f:
            data = f.read()
        try:
            reader = pa.ipc.open_file(io.BytesIO(data))
            for i in range(reader.num_record_batches):
                yield pa.Table.from_batches([reader.get_batch(i)])
        except pa.ArrowInvalid:
            reader = pa.ipc.open_stream(io.BytesIO(data))
            for batch in reader:
                yield pa.Table.from_batches([batch])


# ---------------------------------------------------------------------------
# WAV audio (reference: datasource/audio_datasource.py wraps soundfile;
# stdlib `wave` covers PCM wav without any dependency)
# ---------------------------------------------------------------------------


class AudioDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        if path.lower().endswith(".wav"):
            import wave

            with wave.open(path, "rb") as w:
                rate = w.getframerate()
                nchan = w.getnchannels()
                width = w.getsampwidth()
                raw = w.readframes(w.getnframes())
            dtype = {1: np.uint8, 2: np.int16, 4: np.int32}.get(width)
            if dtype is None:
                raise ValueError(f"unsupported wav sample width {width}")
            arr = np.frombuffer(raw, dtype=dtype).reshape(-1, nchan)
        else:  # non-wav needs soundfile
            try:
                import soundfile
            except ImportError as e:
                raise ImportError(
                    "read_audio for non-wav formats requires soundfile, which "
                    "is not installed in this environment"
                ) from e
            arr, rate = soundfile.read(path)
            arr = np.atleast_2d(np.asarray(arr).T).T
        # (1, n, ch) numeric batch -> fixed-shape tensor column (same
        # FixedSizeList path ImageDatasource uses for HWC tensors)
        cols = {
            "amplitude": arr[None],
            "sample_rate": np.asarray([rate]),
        }
        if self.read_kwargs.get("include_paths"):
            cols["path"] = np.asarray([path], dtype=object)
        yield BlockAccessor.batch_to_block(cols)


# ---------------------------------------------------------------------------
# XML (row-per-element; stdlib ElementTree)
# ---------------------------------------------------------------------------


class XMLDatasource(FileBasedDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        import xml.etree.ElementTree as ET

        record_tag = self.read_kwargs.get("record_tag")
        root = ET.parse(path).getroot()
        elems = root.iter(record_tag) if record_tag else list(root)
        rows = []
        for el in elems:
            row: dict[str, Any] = dict(el.attrib)
            for child in el:
                row[child.tag] = child.text
            if not row and el.text and el.text.strip():
                row["text"] = el.text.strip()
            if row:
                rows.append(row)
        if rows:
            yield BlockAccessor.rows_to_block(rows)


# ---------------------------------------------------------------------------
# Delta Lake (reference: datasource/delta_sharing_datasource.py + the
# deltalake wrapper). Standalone tier: replay the _delta_log JSON actions to
# the live file set, then read those parquets — no deltalake dependency.
# ---------------------------------------------------------------------------


def _delta_live_files(table_path: str) -> list[str]:
    log_dir = os.path.join(table_path, "_delta_log")
    if not os.path.isdir(log_dir):
        raise FileNotFoundError(f"{table_path!r} has no _delta_log")
    live: dict[str, bool] = {}
    versions = sorted(f for f in os.listdir(log_dir) if f.endswith(".json"))
    if not versions:
        raise FileNotFoundError(f"{log_dir!r} has no commit json")
    for fname in versions:
        with open(os.path.join(log_dir, fname)) as f:
            for line in f:
                if not line.strip():
                    continue
                action = json.loads(line)
                if "add" in action:
                    live[action["add"]["path"]] = True
                elif "remove" in action:
                    live.pop(action["remove"]["path"], None)
    return [os.path.join(table_path, p) for p, ok in live.items() if ok]


class DeltaDatasource(Datasource):
    def __init__(self, table_path: str):
        self._inner = ParquetDatasource(_delta_live_files(table_path))

    def estimate_inmemory_data_size(self):
        return self._inner.estimate_inmemory_data_size()

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        return self._inner.get_read_tasks(parallelism)


# ---------------------------------------------------------------------------
# ClickHouse over its HTTP interface (reference: datasource/clickhouse_
# datasource.py wraps clickhouse-connect). Transport injectable for tests.
# ---------------------------------------------------------------------------


def _http_post(url: str, body: bytes, headers: Optional[dict] = None) -> bytes:
    import urllib.request

    req = urllib.request.Request(url, data=body, headers=headers or {})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.read()


class ClickHouseDatasource(Datasource):
    """``query`` runs with ``FORMAT JSONEachRow`` appended; one row per
    JSON line back."""

    def __init__(
        self,
        url: str,
        query: str,
        transport: Callable[[str, bytes], bytes] = None,
    ):
        self._url = url
        self._query = query.rstrip().rstrip(";")
        self._transport = transport or _http_post

    def estimate_inmemory_data_size(self):
        return None

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        url, q, transport = self._url, self._query, self._transport

        def fn():
            raw = transport(url, (q + " FORMAT JSONEachRow").encode())
            rows = [json.loads(ln) for ln in raw.decode().splitlines() if ln.strip()]
            if rows:
                yield BlockAccessor.rows_to_block(rows)

        return [ReadTask(fn, BlockMetadata(None, None))]


# ---------------------------------------------------------------------------
# Databricks SQL warehouses (reference: datasource/databricks_uc_datasource.py
# — REST statement-execution API). Transport injectable for tests.
# ---------------------------------------------------------------------------


class DatabricksDatasource(Datasource):
    def __init__(
        self,
        host: str,
        token: str,
        warehouse_id: str,
        query: str,
        transport: Callable[[str, bytes, dict], bytes] = None,
    ):
        self._host = host.rstrip("/")
        self._token = token
        self._warehouse = warehouse_id
        self._query = query
        self._transport = transport or (
            lambda url, body, headers: _http_post(url, body, headers)
        )

    def estimate_inmemory_data_size(self):
        return None

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        host, token, wh, q, transport = (
            self._host,
            self._token,
            self._warehouse,
            self._query,
            self._transport,
        )

        def fn():
            headers = {
                "Authorization": f"Bearer {token}",
                "Content-Type": "application/json",
            }
            body = json.dumps(
                {
                    "warehouse_id": wh,
                    "statement": q,
                    "wait_timeout": "30s",
                    "format": "JSON_ARRAY",
                    "disposition": "INLINE",
                }
            ).encode()
            resp = json.loads(
                transport(f"{host}/api/2.0/sql/statements/", body, headers).decode()
            )
            state = resp.get("status", {}).get("state")
            if state != "SUCCEEDED":
                raise RuntimeError(f"databricks statement state {state}: {resp}")
            cols = [
                c["name"]
                for c in resp["manifest"]["schema"]["columns"]
            ]
            rows = [dict(zip(cols, r)) for r in resp["result"].get("data_array", [])]
            if rows:
                yield BlockAccessor.rows_to_block(rows)

        return [ReadTask(fn, BlockMetadata(None, None))]


# ---------------------------------------------------------------------------
# Snowflake (reference: datasource/snowflake_datasource.py) — DB-API tier:
# with snowflake-connector installed the connection params work directly;
# any DB-API factory also works (shares SQLDatasource's window machinery).
# ---------------------------------------------------------------------------


def snowflake_datasource(
    query: str,
    connection_factory: Optional[Callable] = None,
    connection_parameters: Optional[dict] = None,
    parallelism_hint: int = 1,
    order_by: Optional[str] = None,
) -> SQLDatasource:
    if connection_factory is None:
        if not connection_parameters:
            raise ValueError(
                "read_snowflake needs connection_factory= or connection_parameters="
            )
        try:
            import snowflake.connector  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "read_snowflake without connection_factory requires "
                "snowflake-connector-python, which is not installed in this "
                "environment; pass connection_factory=... instead"
            ) from e

        def connection_factory():
            import snowflake.connector

            return snowflake.connector.connect(**connection_parameters)

    return SQLDatasource(
        query,
        connection_factory,
        parallelism_hint=parallelism_hint,
        order_by=order_by,
    )


# ---------------------------------------------------------------------------
# Gated imports for formats whose libraries are not in this image
# (reference ships these as first-class sources; the Datasource shim keeps
# the API stable for when the library is present)
# ---------------------------------------------------------------------------


def _gated(name: str, pip_name: str):
    class _Gated(Datasource):
        def __init__(self, *a, **k):
            raise ImportError(
                f"read_{name} requires {pip_name}, which is not installed in "
                f"this environment"
            )

    _Gated.__name__ = f"{name.capitalize()}Datasource"
    return _Gated


class LanceDatasource(Datasource):
    def __init__(self, uri: str, columns=None):
        try:
            import lance
        except ImportError as e:
            raise ImportError(
                "read_lance requires pylance, which is not installed in this "
                "environment"
            ) from e
        self._ds = lance.dataset(uri)
        self._columns = columns

    def estimate_inmemory_data_size(self):
        return None

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        ds, columns = self._ds, self._columns

        def fn():
            yield ds.to_table(columns=columns)

        return [ReadTask(fn, BlockMetadata(None, None))]


class IcebergDatasource(Datasource):
    def __init__(self, table_identifier: str, catalog_kwargs: Optional[dict] = None):
        try:
            from pyiceberg.catalog import load_catalog
        except ImportError as e:
            raise ImportError(
                "read_iceberg requires pyiceberg, which is not installed in "
                "this environment"
            ) from e
        catalog = load_catalog(**(catalog_kwargs or {}))
        self._table = catalog.load_table(table_identifier)

    def estimate_inmemory_data_size(self):
        return None

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        table = self._table

        def fn():
            yield table.scan().to_arrow()

        return [ReadTask(fn, BlockMetadata(None, None))]


HudiDatasource = _gated("hudi", "hudi")


def huggingface_blocks(hf_dataset) -> list:
    """``from_huggingface`` helper: materialize an arrow-backed 🤗 dataset
    into blocks (gated at the call site on the ``datasets`` package)."""
    table = hf_dataset.data.table if hasattr(hf_dataset.data, "table") else hf_dataset.data
    return [table.combine_chunks()]
