"""ray_tpu.data: lazy distributed datasets feeding TPU training.

Reference: ``python/ray/data/__init__.py`` public surface (read_* /
from_* constructors, Dataset, DataIterator, aggregate fns, DataContext).
"""

from __future__ import annotations

from typing import Any, Optional


def _warm_arrow_compute() -> None:
    """Initialize pyarrow's compute-kernel registry NOW, on the importing
    thread, before any arrow garbage exists.

    pyarrow 25's lazy kernel init is not safe against a cyclic-GC pass
    landing mid-init on the same thread: when the first ``take`` runs on a
    background iterator thread of a process that has accumulated arrow
    objects in collectable cycles (exactly what repeated dataset iteration
    produces), the GC's arrow destructors re-enter the half-built registry
    and libarrow NULL-derefs (observed: deterministic ``segfault at 18``
    inside libarrow.so.2500 in ``pc.take`` from ``iter_batches``'s shuffle
    path). Warming once at import, when no cycles exist yet, removes the
    window everywhere — driver and workers alike.
    """
    try:
        import pyarrow as pa
        import pyarrow.compute as pc

        pc.take(pa.table({"x": [0]}), pa.array([0]))
    except Exception:  # pyarrow optional at runtime; data then degrades
        pass


_warm_arrow_compute()

from ray_tpu.data import aggregate  # noqa: F401,E402
from ray_tpu.data.aggregate import AbsMax, AggregateFn, Count, Max, Mean, Min, Std, Sum  # noqa: F401
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata  # noqa: F401
from ray_tpu.data.context import DataContext  # noqa: F401
from ray_tpu.data.dataset import Dataset, GroupedData, MaterializedDataset  # noqa: F401
from ray_tpu.data.datasource import (  # noqa: F401
    BinaryDatasource,
    BlocksDatasource,
    CSVDatasource,
    Datasource,
    FileBasedDatasource,
    ImageDatasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    ReadTask,
    TextDatasource,
    SQLDatasource,
    TFRecordsDatasource,
)
from ray_tpu.data.iterator import DataIterator  # noqa: F401
from ray_tpu.data.plan import LogicalPlan, Read


def _from_source(ds: Datasource, parallelism: int = -1) -> Dataset:
    return Dataset(LogicalPlan([Read(datasource=ds, parallelism=parallelism)]))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    """ds.range(10) → rows {'id': 0..9} (reference: ``ray.data.range``)."""
    return _from_source(RangeDatasource(n), parallelism)


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1) -> Dataset:
    return _from_source(RangeDatasource(n, use_tensor=True, tensor_shape=tuple(shape)), parallelism)


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    return _from_source(ItemsDatasource(list(items)), parallelism)


def from_numpy(arr, *, column: Optional[str] = None) -> Dataset:
    import numpy as np

    from ray_tpu.data.block import TENSOR_COLUMN

    arr = np.asarray(arr)
    block = BlockAccessor.batch_to_block({column or TENSOR_COLUMN: arr})
    return _from_source(BlocksDatasource([block]))


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    blocks = [BlockAccessor.batch_to_block(df) for df in dfs]
    return _from_source(BlocksDatasource(blocks))


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _from_source(BlocksDatasource(list(tables)))


def read_parquet(paths, *, parallelism: int = -1, columns: Optional[list] = None, **kwargs) -> Dataset:
    kw = dict(kwargs)
    if columns is not None:
        kw["columns"] = columns
    return _from_source(ParquetDatasource(paths, kw), parallelism)


def read_csv(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(CSVDatasource(paths, kwargs), parallelism)


def read_json(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(JSONDatasource(paths, kwargs), parallelism)


def read_text(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(TextDatasource(paths, kwargs), parallelism)


def read_binary_files(paths, *, include_paths: bool = False, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(BinaryDatasource(paths, {"include_paths": include_paths, **kwargs}), parallelism)


def read_images(paths, *, size=None, mode: str = "RGB", include_paths: bool = False, parallelism: int = -1) -> Dataset:
    return _from_source(
        ImageDatasource(paths, {"size": size, "mode": mode, "include_paths": include_paths}),
        parallelism,
    )


def read_numpy(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(NumpyDatasource(paths, kwargs), parallelism)


def read_tfrecords(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(TFRecordsDatasource(paths, kwargs), parallelism)


def read_sql(
    sql: str, connection_factory, *, parallelism: int = 1, order_by: str = None
) -> Dataset:
    """Rows of a SQL query as a Dataset (reference: ``ray.data.read_sql``).
    ``connection_factory`` is a zero-arg callable returning a DB-API
    connection (sqlite3.connect, psycopg2.connect, ...). ``parallelism > 1``
    windows the query with LIMIT/OFFSET and requires ``order_by`` (a
    deterministic ordering key) so windows are disjoint."""
    return _from_source(
        SQLDatasource(
            sql, connection_factory, parallelism_hint=parallelism, order_by=order_by
        ),
        parallelism,
    )


def read_webdataset(paths, *, parallelism: int = -1, decode: bool = True, **kwargs) -> Dataset:
    """WebDataset tar shards -> one row per sample (reference:
    ``ray.data.read_webdataset``); columns are member extensions."""
    from ray_tpu.data.datasource import WebDatasetDatasource

    return read_datasource(
        WebDatasetDatasource(paths, {"decode": decode, **kwargs}), parallelism=parallelism
    )


def read_mongo(uri: str, database: str, collection: str, *, pipeline=None, parallelism: int = -1) -> Dataset:
    """MongoDB collection -> Dataset (reference: ``ray.data.read_mongo``).
    Needs pymongo installed."""
    from ray_tpu.data.datasource import MongoDatasource

    return read_datasource(
        MongoDatasource(uri, database, collection, pipeline), parallelism=parallelism
    )


def read_bigquery(project_id: str, *, query: str = None, dataset: str = None, parallelism: int = -1) -> Dataset:
    """BigQuery query/table -> Dataset (reference: ``ray.data.read_bigquery``).
    Needs google-cloud-bigquery installed."""
    from ray_tpu.data.datasource import BigQueryDatasource

    return read_datasource(
        BigQueryDatasource(project_id, query, dataset), parallelism=parallelism
    )


def read_datasource(datasource: Datasource, *, parallelism: int = -1) -> Dataset:
    return _from_source(datasource, parallelism)


# ---------------------------------------------------------------------------
# long-tail sources (datasource_ext.py; reference datasource/ second tranche)
# ---------------------------------------------------------------------------


def read_avro(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    """Avro object-container files -> Dataset (reference:
    ``ray.data.read_avro``). Hand-rolled OCF reader — no fastavro needed
    (null + deflate codecs)."""
    from ray_tpu.data.datasource_ext import AvroDatasource

    return _from_source(AvroDatasource(paths, kwargs), parallelism)


def read_orc(paths, *, parallelism: int = -1, columns: Optional[list] = None, **kwargs) -> Dataset:
    """ORC files via pyarrow.orc (reference: arrow-backed ORC reads)."""
    from ray_tpu.data.datasource_ext import ORCDatasource

    kw = dict(kwargs)
    if columns is not None:
        kw["columns"] = columns
    return _from_source(ORCDatasource(paths, kw), parallelism)


def read_feather(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    """Arrow IPC / Feather v2 files (file or stream format)."""
    from ray_tpu.data.datasource_ext import ArrowIPCDatasource

    return _from_source(ArrowIPCDatasource(paths, kwargs), parallelism)


read_arrow_ipc = read_feather


def read_audio(paths, *, include_paths: bool = False, parallelism: int = -1) -> Dataset:
    """Audio files -> rows of {amplitude, sample_rate} (reference:
    ``ray.data.read_audio``). WAV decodes with the stdlib; other formats
    need soundfile."""
    from ray_tpu.data.datasource_ext import AudioDatasource

    return _from_source(
        AudioDatasource(paths, {"include_paths": include_paths}), parallelism
    )


def read_xml(paths, *, record_tag: str = None, parallelism: int = -1) -> Dataset:
    """XML documents -> one row per record element (attributes + child
    element text become columns)."""
    from ray_tpu.data.datasource_ext import XMLDatasource

    return _from_source(XMLDatasource(paths, {"record_tag": record_tag}), parallelism)


def read_delta(table_path: str, *, parallelism: int = -1) -> Dataset:
    """Delta Lake table -> Dataset by replaying the ``_delta_log`` JSON
    commit actions to the live parquet file set (reference: the deltalake-
    wrapped source; this tier needs no deltalake package)."""
    from ray_tpu.data.datasource_ext import DeltaDatasource

    return _from_source(DeltaDatasource(table_path), parallelism)


def read_clickhouse(url: str, query: str, *, transport=None, parallelism: int = -1) -> Dataset:
    """ClickHouse over its HTTP interface, ``FORMAT JSONEachRow``
    (reference: ``ray.data.read_clickhouse``). ``transport`` is injectable
    for tests / custom auth."""
    from ray_tpu.data.datasource_ext import ClickHouseDatasource

    return read_datasource(
        ClickHouseDatasource(url, query, transport), parallelism=parallelism
    )


def read_databricks_tables(
    *, host: str, token: str, warehouse_id: str, query: str, transport=None,
    parallelism: int = -1,
) -> Dataset:
    """Databricks SQL warehouse statement-execution API (reference:
    ``ray.data.read_databricks_tables``)."""
    from ray_tpu.data.datasource_ext import DatabricksDatasource

    return read_datasource(
        DatabricksDatasource(host, token, warehouse_id, query, transport),
        parallelism=parallelism,
    )


def read_snowflake(
    query: str, *, connection_factory=None, connection_parameters: dict = None,
    parallelism: int = 1, order_by: str = None,
) -> Dataset:
    """Snowflake -> Dataset (reference: ``ray.data.read_snowflake``): pass
    ``connection_parameters`` with snowflake-connector installed, or any
    DB-API ``connection_factory`` (shares read_sql's window machinery)."""
    from ray_tpu.data.datasource_ext import snowflake_datasource

    return _from_source(
        snowflake_datasource(
            query, connection_factory, connection_parameters,
            parallelism_hint=parallelism, order_by=order_by,
        ),
        parallelism,
    )


def read_lance(uri: str, *, columns=None, parallelism: int = -1) -> Dataset:
    """Lance datasets (reference: ``ray.data.read_lance``). Needs pylance."""
    from ray_tpu.data.datasource_ext import LanceDatasource

    return read_datasource(LanceDatasource(uri, columns), parallelism=parallelism)


def read_iceberg(table_identifier: str, *, catalog_kwargs: dict = None, parallelism: int = -1) -> Dataset:
    """Iceberg tables (reference: ``ray.data.read_iceberg``). Needs pyiceberg."""
    from ray_tpu.data.datasource_ext import IcebergDatasource

    return read_datasource(
        IcebergDatasource(table_identifier, catalog_kwargs), parallelism=parallelism
    )


def read_hudi(table_uri: str, *, parallelism: int = -1) -> Dataset:
    """Hudi tables (reference: ``ray.data.read_hudi``). Needs the hudi package."""
    from ray_tpu.data.datasource_ext import HudiDatasource

    return read_datasource(HudiDatasource(table_uri), parallelism=parallelism)


def read_parquet_bulk(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    """Many small parquet files without per-file metadata fetches
    (reference: ``ray.data.read_parquet_bulk`` — same reader here, the
    distinction is advisory)."""
    return read_parquet(paths, parallelism=parallelism, **kwargs)


def from_huggingface(hf_dataset) -> Dataset:
    """An arrow-backed 🤗 ``datasets.Dataset`` -> Dataset (reference:
    ``ray.data.from_huggingface``). Zero-copy: wraps the underlying arrow
    table as blocks."""
    from ray_tpu.data.datasource_ext import huggingface_blocks

    return _from_source(BlocksDatasource(huggingface_blocks(hf_dataset)))
