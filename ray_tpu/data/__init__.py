"""ray_tpu.data: lazy distributed datasets feeding TPU training.

Reference: ``python/ray/data/__init__.py`` public surface (read_* /
from_* constructors, Dataset, DataIterator, aggregate fns, DataContext).
"""

from __future__ import annotations

from typing import Any, Optional


def _warm_arrow_compute() -> None:
    """Initialize pyarrow's compute-kernel registry NOW, on the importing
    thread, before any arrow garbage exists.

    pyarrow 25's lazy kernel init is not safe against a cyclic-GC pass
    landing mid-init on the same thread: when the first ``take`` runs on a
    background iterator thread of a process that has accumulated arrow
    objects in collectable cycles (exactly what repeated dataset iteration
    produces), the GC's arrow destructors re-enter the half-built registry
    and libarrow NULL-derefs (observed: deterministic ``segfault at 18``
    inside libarrow.so.2500 in ``pc.take`` from ``iter_batches``'s shuffle
    path). Warming once at import, when no cycles exist yet, removes the
    window everywhere — driver and workers alike.
    """
    try:
        import pyarrow as pa
        import pyarrow.compute as pc

        pc.take(pa.table({"x": [0]}), pa.array([0]))
    except Exception:  # pyarrow optional at runtime; data then degrades
        pass


_warm_arrow_compute()

from ray_tpu.data import aggregate  # noqa: F401,E402
from ray_tpu.data.aggregate import AbsMax, AggregateFn, Count, Max, Mean, Min, Std, Sum  # noqa: F401
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata  # noqa: F401
from ray_tpu.data.context import DataContext  # noqa: F401
from ray_tpu.data.dataset import Dataset, GroupedData, MaterializedDataset  # noqa: F401
from ray_tpu.data.datasource import (  # noqa: F401
    BinaryDatasource,
    BlocksDatasource,
    CSVDatasource,
    Datasource,
    FileBasedDatasource,
    ImageDatasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    ReadTask,
    TextDatasource,
    SQLDatasource,
    TFRecordsDatasource,
)
from ray_tpu.data.iterator import DataIterator  # noqa: F401
from ray_tpu.data.plan import LogicalPlan, Read


def _from_source(ds: Datasource, parallelism: int = -1) -> Dataset:
    return Dataset(LogicalPlan([Read(datasource=ds, parallelism=parallelism)]))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    """ds.range(10) → rows {'id': 0..9} (reference: ``ray.data.range``)."""
    return _from_source(RangeDatasource(n), parallelism)


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1) -> Dataset:
    return _from_source(RangeDatasource(n, use_tensor=True, tensor_shape=tuple(shape)), parallelism)


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    return _from_source(ItemsDatasource(list(items)), parallelism)


def from_numpy(arr, *, column: Optional[str] = None) -> Dataset:
    import numpy as np

    from ray_tpu.data.block import TENSOR_COLUMN

    arr = np.asarray(arr)
    block = BlockAccessor.batch_to_block({column or TENSOR_COLUMN: arr})
    return _from_source(BlocksDatasource([block]))


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    blocks = [BlockAccessor.batch_to_block(df) for df in dfs]
    return _from_source(BlocksDatasource(blocks))


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _from_source(BlocksDatasource(list(tables)))


def read_parquet(paths, *, parallelism: int = -1, columns: Optional[list] = None, **kwargs) -> Dataset:
    kw = dict(kwargs)
    if columns is not None:
        kw["columns"] = columns
    return _from_source(ParquetDatasource(paths, kw), parallelism)


def read_csv(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(CSVDatasource(paths, kwargs), parallelism)


def read_json(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(JSONDatasource(paths, kwargs), parallelism)


def read_text(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(TextDatasource(paths, kwargs), parallelism)


def read_binary_files(paths, *, include_paths: bool = False, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(BinaryDatasource(paths, {"include_paths": include_paths, **kwargs}), parallelism)


def read_images(paths, *, size=None, mode: str = "RGB", include_paths: bool = False, parallelism: int = -1) -> Dataset:
    return _from_source(
        ImageDatasource(paths, {"size": size, "mode": mode, "include_paths": include_paths}),
        parallelism,
    )


def read_numpy(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(NumpyDatasource(paths, kwargs), parallelism)


def read_tfrecords(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _from_source(TFRecordsDatasource(paths, kwargs), parallelism)


def read_sql(
    sql: str, connection_factory, *, parallelism: int = 1, order_by: str = None
) -> Dataset:
    """Rows of a SQL query as a Dataset (reference: ``ray.data.read_sql``).
    ``connection_factory`` is a zero-arg callable returning a DB-API
    connection (sqlite3.connect, psycopg2.connect, ...). ``parallelism > 1``
    windows the query with LIMIT/OFFSET and requires ``order_by`` (a
    deterministic ordering key) so windows are disjoint."""
    return _from_source(
        SQLDatasource(
            sql, connection_factory, parallelism_hint=parallelism, order_by=order_by
        ),
        parallelism,
    )


def read_webdataset(paths, *, parallelism: int = -1, decode: bool = True, **kwargs) -> Dataset:
    """WebDataset tar shards -> one row per sample (reference:
    ``ray.data.read_webdataset``); columns are member extensions."""
    from ray_tpu.data.datasource import WebDatasetDatasource

    return read_datasource(
        WebDatasetDatasource(paths, {"decode": decode, **kwargs}), parallelism=parallelism
    )


def read_mongo(uri: str, database: str, collection: str, *, pipeline=None, parallelism: int = -1) -> Dataset:
    """MongoDB collection -> Dataset (reference: ``ray.data.read_mongo``).
    Needs pymongo installed."""
    from ray_tpu.data.datasource import MongoDatasource

    return read_datasource(
        MongoDatasource(uri, database, collection, pipeline), parallelism=parallelism
    )


def read_bigquery(project_id: str, *, query: str = None, dataset: str = None, parallelism: int = -1) -> Dataset:
    """BigQuery query/table -> Dataset (reference: ``ray.data.read_bigquery``).
    Needs google-cloud-bigquery installed."""
    from ray_tpu.data.datasource import BigQueryDatasource

    return read_datasource(
        BigQueryDatasource(project_id, query, dataset), parallelism=parallelism
    )


def read_datasource(datasource: Datasource, *, parallelism: int = -1) -> Dataset:
    return _from_source(datasource, parallelism)
