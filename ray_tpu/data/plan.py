"""Logical plan: lazy operator DAG + fusion rules.

Reference: ``python/ray/data/_internal/logical/`` (operators + optimizer
rules) and ``planner/``. The plan here is a linear chain per dataset (unions
and zips hold child plans), optimized by fusing adjacent one-to-one ops into
a single ``MapChain`` so one remote task applies the whole fused transform
per block — the same task-fusion rule the reference's
``OperatorFusionRule`` implements.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Optional

from ray_tpu.data.datasource import Datasource


@dataclass
class LogicalOp:
    name: str = field(default="", init=False)

    def is_one_to_one(self) -> bool:
        return False


@dataclass
class Read(LogicalOp):
    datasource: Datasource
    parallelism: int = -1

    def __post_init__(self):
        self.name = f"Read{self.datasource.name}"


@dataclass
class InputData(LogicalOp):
    """Already-materialized (block_ref, metadata) bundles (e.g. materialize())."""

    bundles: list

    def __post_init__(self):
        self.name = "InputData"


@dataclass
class AbstractMap(LogicalOp):
    fn: Any
    fn_args: tuple = ()
    fn_kwargs: dict = field(default_factory=dict)
    fn_constructor_args: tuple = ()
    fn_constructor_kwargs: dict = field(default_factory=dict)
    compute: Optional[Any] = None  # None => tasks; ActorPoolStrategy => actors
    num_cpus: Optional[float] = None
    num_tpus: Optional[float] = None
    concurrency: Optional[Any] = None

    def is_one_to_one(self) -> bool:
        return True

    def uses_actors(self) -> bool:
        return isinstance(self.fn, type) or self.compute is not None


@dataclass
class MapBatches(AbstractMap):
    batch_size: Optional[int] = None
    batch_format: str = "numpy"
    zero_copy_batch: bool = False

    def __post_init__(self):
        self.name = f"MapBatches({_fn_name(self.fn)})"


@dataclass
class MapRows(AbstractMap):
    def __post_init__(self):
        self.name = f"Map({_fn_name(self.fn)})"


@dataclass
class FlatMap(AbstractMap):
    def __post_init__(self):
        self.name = f"FlatMap({_fn_name(self.fn)})"


@dataclass
class Filter(AbstractMap):
    def __post_init__(self):
        self.name = f"Filter({_fn_name(self.fn)})"


@dataclass
class Project(AbstractMap):
    """select_columns / drop_columns / rename / add_column."""

    def __post_init__(self):
        self.name = "Project"


@dataclass
class Limit(LogicalOp):
    limit: int = 0

    def __post_init__(self):
        self.name = f"Limit({self.limit})"


@dataclass
class AllToAll(LogicalOp):
    """Barrier ops: repartition / random_shuffle / sort / aggregate."""

    kind: str = ""
    options: dict = field(default_factory=dict)

    def __post_init__(self):
        self.name = self.kind.capitalize() or "AllToAll"


@dataclass
class Union(LogicalOp):
    others: list = field(default_factory=list)  # list[LogicalPlan]

    def __post_init__(self):
        self.name = "Union"


@dataclass
class Zip(LogicalOp):
    other: Any = None  # LogicalPlan

    def __post_init__(self):
        self.name = "Zip"


@dataclass
class MapChain(LogicalOp):
    """Fused chain of one-to-one ops, executed inside a single task."""

    ops: list = field(default_factory=list)

    def __post_init__(self):
        self.name = "->".join(op.name for op in self.ops) or "MapChain"

    def is_one_to_one(self) -> bool:
        return True


def _fn_name(fn) -> str:
    return getattr(fn, "__name__", type(fn).__name__)


class LogicalPlan:
    """A chain of logical ops rooted at a Read/InputData."""

    def __init__(self, ops: Optional[list[LogicalOp]] = None):
        self.ops: list[LogicalOp] = ops or []

    def with_op(self, op: LogicalOp) -> "LogicalPlan":
        return LogicalPlan(self.ops + [op])

    def copy(self) -> "LogicalPlan":
        return LogicalPlan(list(self.ops))

    def optimized(self) -> "LogicalPlan":
        """Fuse adjacent one-to-one task-compute ops into MapChains."""
        out: list[LogicalOp] = []
        for op in self.ops:
            fusible = (
                isinstance(op, AbstractMap)
                and not op.uses_actors()
                and op.num_cpus is None
                and op.num_tpus is None
            )
            if (
                fusible
                and out
                and isinstance(out[-1], MapChain)
            ):
                prev = out[-1]
                out[-1] = MapChain(ops=prev.ops + [op])
            elif fusible:
                out.append(MapChain(ops=[op]))
            else:
                out.append(copy.copy(op))
        return LogicalPlan(out)

    def __repr__(self):
        return " -> ".join(op.name for op in self.ops)
