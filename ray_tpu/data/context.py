"""DataContext: per-driver execution knobs.

Reference: ``python/ray/data/context.py`` (DataContext singleton with
target block sizes, op resource limits). Kept deliberately small; every
field is read by the streaming executor.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DataContext:
    # Re-chunk map outputs toward this size (reference default 128 MiB).
    target_max_block_size: int = 128 * 1024 * 1024
    # Max rows per block regardless of bytes (keeps batches bounded).
    target_max_rows_per_block: int = 1_000_000
    # Per-operator concurrent-task cap (reference derives from cluster size).
    max_tasks_per_op: int = 8
    # Global backpressure: pause dispatch when un-consumed downstream output
    # exceeds this many bytes (reference: StreamingExecutor resource budget).
    max_buffered_bytes: int = 2 * 1024 * 1024 * 1024
    # Default parallelism for reads when not specified (-1 = auto).
    read_parallelism: int = -1
    # Min blocks auto parallelism aims for.
    min_parallelism: int = 8
    # Shuffle partitions cap.
    max_shuffle_partitions: int = 64
    # Seed for shuffles when unset.
    shuffle_seed: Optional[int] = None
    # Actor-pool map: max in-flight bundles per actor.
    max_tasks_in_flight_per_actor: int = 2
    enable_operator_fusion: bool = True

    _current: "DataContext" = None  # class-level singleton, set below

    @staticmethod
    def get_current() -> "DataContext":
        if DataContext._current is None:
            DataContext._current = DataContext()
        return DataContext._current
