"""DataIterator: batched consumption with prefetch, plus streaming_split.

Reference: ``python/ray/data/iterator.py`` (iter_batches / iter_torch_batches
/ to_tf) and ``_internal/execution/streaming_split`` (SplitCoordinator actor
serving N concurrent consumers). TPU-first addition: ``iter_jax_batches``
stages numpy column batches onto device with ``jax.device_put`` (optionally
with a NamedSharding) and keeps ``prefetch_batches`` batches in flight so
host→HBM copies overlap the step — the device-feeding role
``iter_torch_batches`` plays in the reference.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


class DataIterator:
    """Iterates batches from a block-producing source (re-iterable)."""

    def __init__(self, bundle_source: Callable[[], Iterator[Any]]):
        # bundle_source: () -> iterator of blocks_refs (each -> list[Block])
        self._source = bundle_source

    # -- raw ----------------------------------------------------------------

    def _iter_blocks(self, prefetch: int) -> Iterator[Block]:
        """Yield blocks as bundles arrive. The ref stream + object fetch run
        on a background thread with a bounded queue: gets overlap with
        consumer compute, and — unlike a hold-back window — an
        already-available block is NEVER gated on the producer's next bundle
        (matters for streaming reads, where the first block can be ready
        seconds before a slow source finishes)."""

        def produce() -> Iterator[Block]:
            for ref in self._source():
                yield from ray_tpu.get(ref)

        if prefetch > 0:
            yield from _bg_prefetch(produce, prefetch)
        else:
            yield from produce()

    def iter_rows(self) -> Iterator[dict]:
        for block in self._iter_blocks(prefetch=1):
            yield from BlockAccessor.for_block(block).iter_rows()

    # -- batches ------------------------------------------------------------

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
        prefetch_batches: int = 1,
    ) -> Iterator[Any]:
        if local_shuffle_buffer_size and batch_size is None:
            raise ValueError("local_shuffle_buffer_size requires a batch_size")

        def produce() -> Iterator[Any]:
            buf: list[Block] = []
            buffered = 0
            min_buffer = local_shuffle_buffer_size or 0
            rng = np.random.default_rng(local_shuffle_seed)
            for block in self._iter_blocks(prefetch_batches):
                acc = BlockAccessor.for_block(block)
                if acc.num_rows() == 0:
                    continue
                buf.append(acc.to_arrow())
                buffered += acc.num_rows()
                if batch_size is None:
                    if not min_buffer:
                        yield _format(buf.pop(), batch_format)
                        buffered = 0
                    continue
                while buffered >= max(batch_size, min_buffer + batch_size):
                    merged = BlockAccessor.concat(buf)
                    macc = BlockAccessor.for_block(merged)
                    if min_buffer:
                        perm = rng.permutation(macc.num_rows())
                        merged = macc.take_indices(perm)
                        macc = BlockAccessor.for_block(merged)
                    head = macc.slice(0, batch_size)
                    buf = [macc.slice(batch_size, macc.num_rows())]
                    buffered = macc.num_rows() - batch_size
                    yield _format(head, batch_format)
            # Drain.
            if buffered and batch_size is not None:
                merged = BlockAccessor.concat(buf)
                macc = BlockAccessor.for_block(merged)
                if min_buffer:
                    perm = rng.permutation(macc.num_rows())
                    merged = macc.take_indices(perm)
                    macc = BlockAccessor.for_block(merged)
                for s in range(0, macc.num_rows(), batch_size):
                    e = min(s + batch_size, macc.num_rows())
                    if e - s < batch_size and drop_last:
                        return
                    yield _format(macc.slice(s, e), batch_format)

        if prefetch_batches > 0:
            yield from _bg_prefetch(produce, prefetch_batches)
        else:
            yield from produce()

    def iter_jax_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        dtypes: Optional[dict] = None,
        sharding: Optional[Any] = None,
        device: Optional[Any] = None,
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        prefetch_batches: int = 2,
        **kwargs,
    ) -> Iterator[dict]:
        """Batches as jax.Arrays already resident on device/sharding."""
        import jax

        for batch in self.iter_batches(
            batch_size=batch_size,
            batch_format="numpy",
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            prefetch_batches=prefetch_batches,
            **kwargs,
        ):
            out = {}
            for k, v in batch.items():
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                if sharding is not None:
                    out[k] = jax.device_put(v, sharding)
                elif device is not None:
                    out[k] = jax.device_put(v, device)
                else:
                    out[k] = jax.device_put(v)
            yield out

    def iter_torch_batches(
        self, *, batch_size: Optional[int] = 256, dtypes=None, device=None, **kwargs
    ) -> Iterator[dict]:
        import torch

        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", **kwargs):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(np.ascontiguousarray(v))
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                if device is not None:
                    t = t.to(device)
                out[k] = t
            yield out

    def materialize(self):
        """Collect this iterator's shard into a materialized Dataset."""
        from ray_tpu.data.dataset import MaterializedDataset, _bundles_from_blocks

        blocks = list(self._iter_blocks(prefetch=2))
        return MaterializedDataset(_bundles_from_blocks(blocks))


def _format(block, batch_format: str):
    acc = BlockAccessor.for_block(block)
    if batch_format in ("numpy", None, "default"):
        return acc.to_numpy_batch()
    if batch_format == "pandas":
        return acc.to_pandas()
    if batch_format == "pyarrow":
        return acc.to_arrow()
    raise ValueError(f"Unknown batch_format {batch_format!r}")


def _bg_prefetch(produce: Callable[[], Iterator], depth: int) -> Iterator:
    """Run the producer on a thread with a bounded queue (overlaps object
    fetch + format conversion with consumer compute). If the consumer
    abandons the iterator early, the stop event unblocks the producer so the
    underlying executor generator is closed (actor pools shut down, refs
    released) instead of leaking a thread parked on a full queue."""
    q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
    stop = threading.Event()
    DONE, ERR = object(), object()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def run():
        gen = produce()
        try:
            for item in gen:
                if not put(item):
                    return
            put(DONE)
        except BaseException as e:  # noqa: BLE001 — propagate to consumer
            put((ERR, e))
        finally:
            close = getattr(gen, "close", None)
            if close:
                close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is DONE:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] is ERR:
                raise item[1]
            yield item
    finally:
        stop.set()


class SplitCoordinator:
    """Actor distributing one streaming execution across N consumers.

    Reference: ``python/ray/data/_internal/execution/streaming_split``
    (SplitCoordinator). Each epoch re-runs the plan; consumers pull bundles
    round-robin-by-arrival; with ``equal=True`` every consumer sees the same
    number of bundles, and the trailing partial group is re-sliced at row
    granularity so each consumer also sees the same number of rows (only the
    sub-``n`` row remainder is dropped).
    """

    def __init__(self, plan, n: int, equal: bool):
        self._plan = plan
        self._n = n
        self._equal = equal
        self._lock = threading.Lock()
        # Per-epoch queue sets: a lagging consumer keeps draining ITS epoch's
        # queues even after a faster consumer has started the next epoch.
        self._epochs: dict[int, list[queue.Queue]] = {}
        self._finished_counts: dict[int, int] = {}

    def start_epoch(self, epoch: int) -> bool:
        with self._lock:
            if epoch in self._epochs or epoch in self._finished_counts:
                return False
            # Bounded: consumers lagging behind apply backpressure to the
            # executor thread instead of buffering the whole dataset.
            queues = [queue.Queue(maxsize=8) for _ in range(self._n)]
            self._epochs[epoch] = queues
            self._finished_counts[epoch] = 0
            threading.Thread(target=self._pump, args=(queues,), daemon=True).start()
            return True

    def _pump(self, queues):
        from ray_tpu.data.execution import StreamingExecutor

        try:
            i = 0
            pending: list = []
            for bundle in StreamingExecutor(self._plan.copy()):
                if self._equal:
                    pending.append(bundle.blocks_ref)
                    if len(pending) == self._n:
                        for qi, ref in zip(queues, pending):
                            qi.put(ref)
                        pending = []
                else:
                    queues[i % self._n].put(bundle.blocks_ref)
                    i += 1
            if self._equal and pending:
                self._split_remainder_rows(queues, pending)
            for qi in queues:
                qi.put(None)
        except BaseException as e:  # noqa: BLE001
            for qi in queues:
                qi.put(("__err__", repr(e)))

    def _split_remainder_rows(self, queues, pending):
        """equal=True tail: fewer trailing bundles than consumers. The
        reference equalizes at ROW granularity (``streaming_split``
        SplitCoordinator) — slice the leftover bundles' rows evenly across
        all consumers instead of silently dropping them (with coarse
        bundles that tail can be a large fraction of the epoch)."""
        import ray_tpu
        from ray_tpu.data.block import BlockAccessor

        blocks = []
        for ref in pending:
            blocks.extend(ray_tpu.get(ref))
        total = sum(BlockAccessor.for_block(b).num_rows() for b in blocks)
        per = total // self._n
        if per <= 0:
            return  # fewer rows than consumers — nothing equal to hand out
        parts: list[list] = [[] for _ in range(self._n)]
        qi, need = 0, per
        for b in blocks:
            acc = BlockAccessor.for_block(b)
            off, n_rows = 0, acc.num_rows()
            while off < n_rows and qi < self._n:
                take = min(need, n_rows - off)
                parts[qi].append(acc.slice(off, off + take))
                off += take
                need -= take
                if need == 0:
                    qi += 1
                    need = per
        for q, blks in zip(queues, parts):
            if blks:
                q.put(ray_tpu.put(blks))

    def next_bundle(self, split_idx: int, epoch: int):
        """Blocking pull; returns a blocks_ref or None at end of epoch."""
        self.start_epoch(epoch)
        with self._lock:
            queues = self._epochs.get(epoch)
        if queues is None:  # this consumer already saw end-of-epoch
            return None
        item = queues[split_idx].get()
        if isinstance(item, tuple) and item and item[0] == "__err__":
            raise RuntimeError(f"streaming_split execution failed: {item[1]}")
        if item is None:
            with self._lock:
                self._finished_counts[epoch] += 1
                if self._finished_counts[epoch] >= self._n:
                    self._epochs.pop(epoch, None)
        return item


class SplitIterator(DataIterator):
    """One consumer's view of a SplitCoordinator."""

    def __init__(self, coordinator, split_idx: int):
        self._coord = coordinator
        self._idx = split_idx
        self._epoch = 0
        super().__init__(self._pull)

    def _pull(self):
        epoch = self._epoch
        self._epoch += 1
        while True:
            ref = ray_tpu.get(self._coord.next_bundle.remote(self._idx, epoch))
            if ref is None:
                return
            yield ref
