"""Live observability CLI: ``python -m ray_tpu.obs <command>``.

Reference: the state CLI (``ray summary`` / ``ray list`` /
``ray timeline``) plus the dashboard's live cluster view, folded into one
terminal tool over this repo's three observability surfaces:

* ``util.metrics`` — cluster-merged counters/gauges/histograms (now with
  bucket-interpolated percentile snapshots),
* the flight recorder (``_private/events.py``) — every process's
  always-on ring of structured events, drained live through the head,
* ``util.tracing`` — spans + task events correlated by ``request_id``.

Commands::

    python -m ray_tpu.obs top --address HOST:PORT [--watch 2]
        Live cluster + LLM engine view: nodes, tasks by state,
        running/waiting requests, KV utilization, speculative acceptance
        rate, tokens/s, TTFT/ITL p50/p95/p99.

    python -m ray_tpu.obs req <request_id> --address HOST:PORT
        One request's life as a timeline: proxy -> replica -> engine
        events (admission, prefill chunks, first token, per-step
        decode/verify with accepted counts, preemptions, finish), with
        relative timestamps and a latency summary.

    python -m ray_tpu.obs attribute --address HOST:PORT [--top 10]
        Request latency attribution: joins the per-request phase ledgers
        (``llm.phase.*`` events, live drain + crash-flush rings) into
        per-phase p50/p95/p99, the slowest requests with their dominant
        phase, and the p99-budget identity (phases sum to end-to-end
        within ε).

    python -m ray_tpu.obs events --address HOST:PORT [--tail 50]
        Tail the cluster-wide flight recorder (newest last).

    python -m ray_tpu.obs timeline --address HOST:PORT -o trace.json
        Chrome-trace export (task events + spans + one lane per request);
        load in chrome://tracing or Perfetto.

    python -m ray_tpu.obs series llm_generated_tokens --address HOST:PORT
        Metric history without Grafana: sparkline of the rate (counters) /
        value (gauges) / observations-per-second + windowed percentiles
        (histograms), from the head-drained time-series rings.

    python -m ray_tpu.obs alerts --address HOST:PORT [--eval-once]
        The SLO burn-rate engine's state: every rule with FIRING/OK/
        RESOLVED status, current burn value, firing age, and labels.

    python -m ray_tpu.obs waterfall --address HOST:PORT [--probe N]
        Task-hop waterfall: the head's per-phase histograms (submit →
        serialize → socket-write → head-dispatch → worker-deserialize →
        exec → reply, plus total) folded from sampled tasks' stamp
        lists, rendered as a p50/p95/p99 table.  ``--probe N`` first
        drives N sync noop tasks under a traced context so a fresh
        cluster has data (the CI waterfall-probe job does exactly this
        and uploads the --json output).

    python -m ray_tpu.obs objects --address HOST:PORT [--top 20] [--audit]
        The object-plane ledger: every directory entry's state (inline /
        arena / segment / spilled / poisoned), owner node, size, ref and
        pin counts, and age, largest first, plus the freed-forensics
        tail.  ``--audit`` runs the cluster-wide leak audit (orphaned
        arena bytes, dangling locators, orphaned/missing spill files,
        stale pins) and exits non-zero when it finds anything — CI runs
        it after the chaos suite.

    python -m ray_tpu.obs arena --address HOST:PORT
        Per-node arena residency bars: occupancy against capacity with
        the 90% degrade watermark marked, pinned bytes, live pin count
        and oldest pin age, and bytes spilled to disk.

    python -m ray_tpu.obs export -o otlp.json --address HOST:PORT
        OTLP-JSON export of spans, flight-recorder events, and metric
        series (resourceSpans/resourceLogs/resourceMetrics in one file);
        --events-dir exports crash-flush postmortems with no cluster, and
        RAY_TPU_OTLP_ENDPOINT (or --post) adds a best-effort HTTP sink.

Every command needs a running cluster (``--address``, or
``RAY_TPU_ADDRESS``); ``req``/``events`` also read crash-flush JSONL
files from ``--events-dir`` so a killed worker's last events still show.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from typing import Optional

# `obs overhead` probe metrics (raylint RL012 registry): created only by
# measure_overhead() in the probing process, never in a serving cluster
METRIC_NAMES = (
    "obs_overhead_counter",
    "obs_overhead_gauge",
    "obs_overhead_hist",
)


def _attach(address: Optional[str]):
    import ray_tpu

    ray_tpu.init(address=address or os.environ.get("RAY_TPU_ADDRESS") or None)
    return ray_tpu


def _offline(args) -> bool:
    """True when the command should run purely from crash-flush JSONL:
    an explicit --events-dir and no address to attach to.  Booting a
    fresh local cluster just to read files off disk would be slow, can
    fail in restricted sandboxes, and contributes zero events — the
    postmortem flow (CI artifact triage, a dead cluster's events dir)
    must work with nothing alive."""
    return bool(
        getattr(args, "events_dir", None)
        and not (args.address or os.environ.get("RAY_TPU_ADDRESS"))
    )


def _fmt_ms(seconds: float) -> str:
    return f"{seconds * 1e3:.1f}ms"


def _fmt_pcts(p: dict) -> str:
    def one(v):
        return "-" if v is None or (isinstance(v, float) and math.isnan(v)) else _fmt_ms(v)

    return (
        f"p50={one(p.get('p50'))} p95={one(p.get('p95'))} "
        f"p99={one(p.get('p99'))} (n={p.get('count', 0)})"
    )


def hist_pcts_row(p: Optional[dict]) -> str:
    """Percentile summary honoring the below-2-samples contract shared by
    every series-derived row (waterfall_top_row, core_batch_top_row, the
    phase tables): fewer than two observations renders ``—``, never a
    percentile faked out of one sample."""
    if not p or p.get("count", 0) < 2:
        return "—"
    return _fmt_pcts(p)


def _first_series(per_tag: dict):
    """A metric's sole (or first) tagged series — engine metrics are
    untagged, so this is the value."""
    for v in per_tag.values():
        return v
    return None


def _load_crash_files(events_dir: Optional[str]) -> list[dict]:
    """Crash-flush JSONL files (``events.flush``) — the postmortem side of
    ``events``/``req``: a killed worker can't answer the live drain, but
    its flushed ring is still on disk."""
    from ray_tpu._private import events as ev

    return ev.load_crash_files(events_dir)


# ---------------------------------------------------------------------------
# top
# ---------------------------------------------------------------------------


def _series_rate(merged: dict, name: str) -> Optional[float]:
    """Newest delta/dt of a cluster-merged counter series (summed across
    tagsets), or None when fewer than 2 samples exist — a one-frame
    ``obs top`` must never fake a rate out of a lifetime counter."""
    from ray_tpu.util.metrics import latest_rate

    ent = merged.get(name)
    if not ent:
        return None
    rates = [
        r for r in (latest_rate(points) for points in ent["series"].values())
        if r is not None
    ]
    if not rates:
        return None
    return sum(rates)


def _series_rate_text(merged: dict, name: str) -> str:
    rate = _series_rate(merged, name)
    return "—" if rate is None else f"{rate:.1f}"


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0 or unit == "TB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}TB"


def _render_top() -> None:
    """One frame of ``obs top``. Rates come from the metric time-series
    (delta/dt of the head-drained rings), not lifetime counters."""
    from ray_tpu.util import state as st
    from ray_tpu.util.metrics import collect, collect_series, histogram_percentiles

    data = collect()
    metrics = data.get("metrics", {})
    series = collect_series()
    summary = st.summary()
    nodes = [n for n in st.list_nodes() if n.get("Alive", n.get("alive", True))]

    def gauge(name, default=None):
        v = _first_series(metrics.get(name, {}))
        return default if v is None else v

    lines = [
        time.strftime("-- ray_tpu obs top -- %H:%M:%S"),
        f"nodes: {len(nodes)}  "
        f"tasks: {summary.get('tasks', {}).get('by_state') or {}}  "
        f"actors: {summary.get('actors', {}).get('by_state') or {}}",
    ]
    req_rate = _series_rate_text(series, "serve_requests")
    if req_rate != "—":
        lines.append(f"serve: requests/s={req_rate}")
    wf_line = _waterfall_top_line()
    if wf_line:
        lines.append(wf_line)
    batch_line = core_batch_top_row(metrics, histogram_percentiles())
    if batch_line:
        lines.append(batch_line)
    dp_line = core_data_plane_top_row(metrics, series)
    if dp_line:
        lines.append(dp_line)
    if "llm_running_requests" in metrics:
        acc = gauge("llm_spec_acceptance_rate")
        # runtime retrace count (device_prof): nonzero after warmup means
        # a jit site is recompiling mid-traffic (RL014's runtime twin)
        retraces = sum(
            v
            for v in metrics.get("device_retraces", {}).values()
            if isinstance(v, (int, float))
        )
        lines.append(
            "engine: "
            f"running={int(gauge('llm_running_requests', 0) or 0)} "
            f"waiting={int(gauge('llm_waiting_requests', 0) or 0)} "
            f"kv_util={float(gauge('llm_kv_block_utilization', 0.0) or 0.0):.2f} "
            f"tokens/step={gauge('llm_tokens_per_step', 0)} "
            + (f"accept_rate={acc:.2f} " if acc is not None else "")
            + (
                f"retraces={int(retraces)} "
                if "device_retraces" in metrics
                else ""
            )
            + f"tokens/s={_series_rate_text(series, 'llm_generated_tokens')} "
            + f"req/s={_series_rate_text(series, 'llm_finished_requests')}"
        )
        pcts = histogram_percentiles()
        ttft = _first_series(pcts.get("llm_time_to_first_token_s", {}))
        itl = _first_series(pcts.get("llm_inter_token_latency_s", {}))
        if ttft:
            lines.append(f"TTFT: {hist_pcts_row(ttft)}")
        if itl:
            lines.append(f"ITL:  {hist_pcts_row(itl)}")
    else:
        lines.append("engine: (no llm_* metrics published — no LLM replica running)")
    firing = _firing_alerts()
    if firing:
        lines.append(
            "ALERTS: " + "  ".join(
                f"{a['rule']}=FIRING({a['value']:.2f})" for a in firing
            )
        )
    print("\n".join(lines), flush=True)


def core_batch_top_row(metrics: dict, pcts: dict) -> Optional[str]:
    """The ``obs top`` task-plane batching row (ISSUE 14): submit-window
    and reply-batch size p50/p99 plus the submitter's remaining window
    credits. Same below-2-samples contract as the waterfall row — a
    histogram with fewer than two observations renders ``—``."""
    if (
        "core_submit_batch_size" not in metrics
        and "core_reply_batch_size" not in metrics
    ):
        return None

    def hist(name: str) -> str:
        p = _first_series(pcts.get(name, {})) or {}
        if p.get("count", 0) < 2:
            return "—"
        return f"{p['p50']:.0f}/{p['p99']:.0f}"

    credits = _first_series(metrics.get("core_submit_credits", {}))
    return (
        "core-batch(p50/p99): "
        f"submit={hist('core_submit_batch_size')} "
        f"reply={hist('core_reply_batch_size')}"
        + (f" credits={int(credits)}" if credits is not None else "")
    )


def core_data_plane_top_row(metrics: dict, series: dict) -> Optional[str]:
    """The ``obs top`` data-plane row (ISSUE 19): shm put/get throughput
    (rates from the drained time-series, same below-2-samples ``—``
    contract as every other rate on the frame), the zero-copy locality
    hit rate (lifetime local hits over all shm reads), and the worst
    node's arena occupancy."""
    if (
        "core_shm_put_bytes" not in metrics
        and "core_shm_get_bytes" not in metrics
        and "core_arena_occupancy" not in metrics
    ):
        return None

    def mbps(name: str) -> str:
        rate = _series_rate(series, name)
        return "—" if rate is None else f"{rate / (1 << 20):.1f}"

    def total(name: str) -> float:
        return sum(
            v for v in metrics.get(name, {}).values()
            if isinstance(v, (int, float))
        )

    parts = [f"put={mbps('core_shm_put_bytes')}MB/s",
             f"get={mbps('core_shm_get_bytes')}MB/s"]
    reads = total("core_data_local_hits") + total("core_data_remote_pulls")
    if reads:
        parts.append(f"local={total('core_data_local_hits') / reads:.0%}")
    occ = _first_series(metrics.get("core_arena_occupancy", {}))
    if occ is not None:
        parts.append(f"arena={float(occ):.0%}")
    return "data-plane: " + " ".join(parts)


def waterfall_top_row(summary: dict) -> str:
    """The ``obs top`` waterfall row: per-hop ``p50/p99`` from the head's
    phase histograms, honoring the below-2-samples contract — a hop that
    has fewer than two folded samples renders ``—``, never a number
    faked out of one observation."""
    parts = []
    for name, _i, _j in _wf_legs():
        p = summary.get("legs", {}).get(name) or {}
        if p.get("count", 0) < 2:
            parts.append(f"{name}=—")
        else:
            parts.append(f"{name}={_fmt_us(p['p50'])}/{_fmt_us(p['p99'])}")
    return "waterfall(p50/p99): " + " ".join(parts)


def _wf_legs():
    from ray_tpu.util.waterfall import LEGS

    return LEGS


def _fmt_us(seconds: float) -> str:
    if seconds is None or (isinstance(seconds, float) and math.isnan(seconds)):
        return "-"
    if seconds >= 0.1:
        return f"{seconds:.2f}s"
    if seconds >= 0.001:
        return f"{seconds * 1e3:.1f}ms"
    return f"{seconds * 1e6:.0f}us"


def _waterfall_top_line() -> Optional[str]:
    try:
        from ray_tpu._private.runtime import get_ctx

        s = get_ctx().call("waterfall")
    except Exception:
        return None
    if not s or not s.get("folded"):
        return None
    return waterfall_top_row(s)


def _firing_alerts() -> list[dict]:
    try:
        from ray_tpu._private.runtime import get_ctx

        return [a for a in get_ctx().call("alerts") if a.get("status") == "FIRING"]
    except Exception:
        return []


def cmd_top(args) -> int:
    ray_tpu = _attach(args.address)
    try:
        while True:
            _render_top()
            if args.once:
                return 0
            time.sleep(max(args.watch, 0.2))
            print()
    except KeyboardInterrupt:
        return 0
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# series / alerts / export
# ---------------------------------------------------------------------------

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float], width: int = 48) -> str:
    """Terminal sparkline of the newest ``width`` values."""
    vals = [v for v in values[-width:] if v is not None]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(_SPARK[int((v - lo) / span * (len(_SPARK) - 1))] for v in vals)


def render_series(name: str, ent: dict, window_s: float) -> str:
    """One metric's history as text: per-tagset sparkline + summary.
    Counters render as rates, gauges as raw values, histograms as an
    observations/s sparkline plus a percentile summary over the window."""
    from ray_tpu.util.metrics import (
        series_percentiles_over_window,
        series_rate,
    )

    kind = ent.get("kind", "counter")
    lines = [f"{name} ({kind})"]
    for tagset, points in sorted(ent.get("series", {}).items()):
        label = tagset or "(untagged)"
        if kind == "histogram":
            counts = [(ts, v[-1]) for ts, v in points if isinstance(v, (list, tuple))]
            rates = series_rate(counts)
            pct = series_percentiles_over_window(
                points, ent.get("boundaries") or (), window_s
            )
            if rates:
                lines.append(
                    f"  {label}: obs/s {sparkline([r for _t, r in rates])}  "
                    f"last={rates[-1][1]:.1f}/s"
                )
            else:
                lines.append(f"  {label}: — (needs ≥2 samples)")
            lines.append(f"    window {int(window_s)}s: {_fmt_pcts(pct)}")
        elif kind == "counter":
            rates = series_rate(points)
            if rates:
                lines.append(
                    f"  {label}: rate {sparkline([r for _t, r in rates])}  "
                    f"last={rates[-1][1]:.1f}/s"
                )
            else:
                lines.append(f"  {label}: — (needs ≥2 samples)")
        else:
            vals = [float(v) for _t, v in points]
            if vals:
                lines.append(
                    f"  {label}: {sparkline(vals)}  last={vals[-1]:.3f}"
                )
            else:
                lines.append(f"  {label}: (no samples)")
    if len(lines) == 1:
        lines.append("  (no series — metric never sampled)")
    return "\n".join(lines)


def cmd_series(args) -> int:
    from ray_tpu.util.metrics import collect_series

    ray_tpu = _attach(args.address)
    try:
        merged = collect_series(args.metric or None)
        if args.metric:
            ent = merged.get(args.metric)
            if ent is None:
                print(f"no series for metric {args.metric!r}")
                return 1
            print(render_series(args.metric, ent, args.window))
        else:
            for name in sorted(merged):
                print(render_series(name, merged[name], args.window))
        return 0
    finally:
        ray_tpu.shutdown()


def render_alerts(alerts: list[dict]) -> str:
    """The ``obs alerts`` table: rule, status, value, age, labels."""
    if not alerts:
        return "no SLO rules registered"
    now = time.time()
    lines = [f"{'RULE':<22} {'STATUS':<9} {'VALUE':>9}  {'SINCE':>8}  DETAIL"]
    for a in alerts:
        since = a.get("since")
        age = f"{now - since:.0f}s" if since else "-"
        detail = a.get("detail") or {}
        parts = []
        if "fast_burn" in detail:
            parts.append(
                f"burn fast={detail['fast_burn']:.2f} slow={detail.get('slow_burn', 0):.2f}"
            )
        if detail.get("no_data"):
            parts.append("no data")
        if a.get("labels"):
            parts.append(",".join(f"{k}={v}" for k, v in a["labels"].items()))
        lines.append(
            f"{a['rule']:<22} {a['status']:<9} {a.get('value', 0.0):>9.3f}  "
            f"{age:>8}  {' '.join(parts)}"
        )
    return "\n".join(lines)


def cmd_alerts(args) -> int:
    from ray_tpu._private.runtime import get_ctx

    ray_tpu = _attach(args.address)
    try:
        alerts = get_ctx().call("alerts", eval_now=bool(args.eval_once))
        if args.json:
            print(json.dumps(alerts, default=repr))
        else:
            print(render_alerts(alerts))
        return 0
    finally:
        ray_tpu.shutdown()


def cmd_export(args) -> int:
    from ray_tpu.util import otlp

    offline = _offline(args)
    ray_tpu = None
    if not offline:
        ray_tpu = _attach(args.address)
    try:
        doc, counts = otlp.export_cluster(
            path=args.output, events_dir=args.events_dir, offline=offline
        )
        posted = otlp.post(doc) if (args.post or otlp.otlp_endpoint()) else {}
        where = "offline, crash-flush only" if offline else "live cluster"
        print(
            f"wrote OTLP export to {args.output} ({where}): "
            f"{counts['spans']} spans, {counts['events']} events, "
            f"{counts['metrics']} metric series"
        )
        for path, status in posted.items():
            print(f"  POST {path}: {status}")
        return 0
    finally:
        if ray_tpu is not None:
            ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# waterfall: the task-plane phase breakdown (head-folded histograms)
# ---------------------------------------------------------------------------


def render_waterfall(summary: dict) -> str:
    """The ``obs waterfall`` table: one row per phase with p50/p95/p99
    and sample count (``—`` below 2 samples, same contract as top)."""
    lines = [
        f"task-hop waterfall: {summary.get('folded', 0)} folded, "
        f"{summary.get('incomplete', 0)} incomplete",
        f"{'PHASE':<20} {'N':>6}  {'P50':>9} {'P95':>9} {'P99':>9}",
    ]
    for name, _i, _j in _wf_legs():
        p = summary.get("legs", {}).get(name) or {}
        n = p.get("count", 0)
        if n < 2:
            lines.append(f"{name:<20} {n:>6}  {'—':>9} {'—':>9} {'—':>9}")
            continue
        lines.append(
            f"{name:<20} {n:>6}  {_fmt_us(p['p50']):>9} "
            f"{_fmt_us(p['p95']):>9} {_fmt_us(p['p99']):>9}"
        )
    return "\n".join(lines)


def run_waterfall_probe(n: int) -> None:
    """Drive ``n`` sync noop tasks under one traced (sampled) context so
    the head folds a full waterfall per task — the burst ``obs waterfall
    --probe`` and the CI waterfall-probe job measure.  Sync on purpose:
    one submit→reply round trip per task is the per-task IPC cost the
    100k-tasks/s work needs broken down, with no pipelining to blur it."""
    import ray_tpu
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def _wf_probe_noop(i):
        return i

    with tracing.trace_context():
        for i in range(n):
            ray_tpu.get(_wf_probe_noop.remote(i))


def cmd_waterfall(args) -> int:
    from ray_tpu._private.runtime import get_ctx

    ray_tpu = _attach(args.address)
    try:
        if args.probe:
            run_waterfall_probe(args.probe)
        s = get_ctx().call("waterfall", recent=args.recent)
        if args.json:
            print(json.dumps(s))
        else:
            print(render_waterfall(s))
            for rec in s.get("recent", []):
                print(json.dumps(rec))
        return 0 if s.get("folded") else 1
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# overhead: self-measured emit-path costs (no cluster needed)
# ---------------------------------------------------------------------------


def measure_overhead(n: int = 200_000) -> dict:
    """Microbenchmark the telemetry hot paths IN THIS PROCESS: ns per
    flight-recorder event, per unsampled trace context (mint + span),
    per counter increment / gauge set / histogram observe.  These are the
    numbers the OBSERVABILITY.md overhead budget pins — one command to
    spot a hot-path regression without booting a cluster."""
    from ray_tpu._private import events as ev
    from ray_tpu.util import metrics as um
    from ray_tpu.util import tracing as tr

    def bench(fn, k=n) -> float:
        fn()  # warm (ring/cell/context creation off the measured loop)
        t0 = time.perf_counter_ns()
        for _ in range(k):
            fn()
        return (time.perf_counter_ns() - t0) / k

    out: dict = {"n": n}

    prev_enabled = ev.enabled()
    ev.set_enabled(True)
    out["event_record_ns"] = bench(lambda: ev.record("obs.overhead", i=1))
    ev.set_enabled(False)
    out["event_record_disabled_ns"] = bench(lambda: ev.record("obs.overhead"))
    ev.set_enabled(prev_enabled)

    # unsampled context: the mint decision + installing the token + a
    # span that must short-circuit (the zero-cost tracing contract)
    prev_rate = os.environ.get("RAY_TPU_TRACE_SAMPLE")
    os.environ["RAY_TPU_TRACE_SAMPLE"] = "0"
    try:
        def unsampled_hop():
            with tr.trace_context():
                with tr.span("obs.overhead"):
                    pass

        # per-REQUEST cost: mint (sampling decision + id) + install + one span
        out["unsampled_context_ns"] = bench(unsampled_hop, k=max(1, n // 4))

        # per-SPAN cost under an already-unsampled context — the
        # "unsampled tracing is free" contract is THIS number
        prev_ctx = tr.set_trace_context(tr.mint_context())

        def unsampled_span():
            with tr.span("obs.overhead"):
                pass

        out["unsampled_span_ns"] = bench(unsampled_span)
        tr.set_trace_context(prev_ctx)
    finally:
        if prev_rate is None:
            os.environ.pop("RAY_TPU_TRACE_SAMPLE", None)
        else:
            os.environ["RAY_TPU_TRACE_SAMPLE"] = prev_rate

    c = um.Counter("obs_overhead_counter", "obs overhead probe")
    out["counter_inc_ns"] = bench(c.inc)
    g = um.Gauge("obs_overhead_gauge", "obs overhead probe")
    out["gauge_set_ns"] = bench(lambda: g.set(1.0))
    h = um.Histogram("obs_overhead_hist", "obs overhead probe")
    out["histogram_observe_ns"] = bench(lambda: h.observe(0.5))

    # task-hop waterfall emit paths (util.waterfall): the sampled path is
    # one clock read + list append per stamp; the UNSAMPLED path — what
    # every untraced task pays at submit — must cost no more than a
    # disabled record() (one type check; tests/test_obs_hotpath.py pins
    # the ratio)
    from ray_tpu.util import device_prof as dp
    from ray_tpu.util import waterfall as wfl

    out["waterfall_stamp_ns"] = bench(lambda: wfl.stamp([0.0]))
    out["waterfall_unsampled_ns"] = bench(lambda: wfl.maybe_start(None))

    # request phase-ledger charge (util.phases): the per-stamp cost every
    # engine phase transition pays — the ≤2µs/stamp budget's probe
    from ray_tpu.util import phases as ph

    led = ph.new_ledger(time.time())
    out["phase_charge_ns"] = bench(lambda: ph.charge(led, ph.DECODE, 1.0))

    # device-step profiler emit path (cache-size probe + tagged observe);
    # the probe target has no _cache_size, like any non-jit callable
    prof = dp.JitProfiler(event="obs.overhead.retrace")

    def _plain():
        return None

    out["device_prof_note_ns"] = bench(lambda: prof.note("probe", _plain, 1e-4))
    return {k: round(v, 1) if isinstance(v, float) else v for k, v in out.items()}


def cmd_overhead(args) -> int:
    res = measure_overhead(args.n)
    if args.json:
        print(json.dumps(res))
        return 0
    print(f"telemetry emit-path self-measurement ({res['n']} iterations):")
    rows = [
        ("flight-recorder record()", res["event_record_ns"]),
        ("record() while disabled", res["event_record_disabled_ns"]),
        ("unsampled trace ctx + span", res["unsampled_context_ns"]),
        ("span under unsampled ctx", res["unsampled_span_ns"]),
        ("Counter.inc()", res["counter_inc_ns"]),
        ("Gauge.set()", res["gauge_set_ns"]),
        ("Histogram.observe()", res["histogram_observe_ns"]),
        ("waterfall stamp (sampled)", res["waterfall_stamp_ns"]),
        ("waterfall check (unsampled)", res["waterfall_unsampled_ns"]),
        ("phase-ledger charge()", res["phase_charge_ns"]),
        ("step-profiler note()", res["device_prof_note_ns"]),
    ]
    for label, v in rows:
        print(f"  {label:<28} {v:>9.1f} ns")
    return 0


# ---------------------------------------------------------------------------
# req
# ---------------------------------------------------------------------------


def request_events(request_id: str, events_dir: Optional[str] = None) -> list[dict]:
    """Everything known about one request, merged and time-ordered: live
    flight-recorder rings (cluster drain), crash-flush files, and span/
    task-event records tagged with the id."""
    from ray_tpu._private import events as ev
    from ray_tpu.util import state as st
    from ray_tpu.util import tracing

    merged = ev.collect_cluster_events(request_id)
    for rec in _load_crash_files(events_dir):
        if rec.get("request_id") == request_id:
            merged.append(rec)
    # spans (cluster-wide) whose args carry the id become span events
    for s in tracing.collect_cluster_spans():
        if (s.get("args") or {}).get("request_id") != request_id:
            continue
        merged.append(
            {
                "ts": s["ts"] / 1e6,
                "type": f"span:{s['name']}",
                "dur_s": round(s.get("dur", 0.0) / 1e6, 6),
                "request_id": request_id,
                "pid": s.get("pid"),
            }
        )
    # runtime task events (submitted/running/finished hops)
    try:
        for t in st.get_task_events():
            if t.get("request_id") != request_id:
                continue
            merged.append(
                {
                    "ts": t["time"],
                    "type": f"task:{t.get('name') or t['task_id'][:8]}:{t['state']}",
                    "request_id": request_id,
                }
            )
    except Exception:
        pass  # state API gone (detached postmortem): recorder data stands alone
    merged.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    return _dedup(merged)


def _dedup(evs: list[dict]) -> list[dict]:
    """Drop events that arrived through more than one channel (the live
    drain AND a crash-flush file — a process that flushed but survived
    answers both), keyed on per-process identity."""
    seen = set()
    out = []
    for e in evs:
        key = (e.get("ts"), e.get("type"), e.get("pid"), e.get("seq"))
        if key in seen:
            continue
        seen.add(key)
        out.append(e)
    return out


def render_request(request_id: str, evs: list[dict]) -> str:
    """Human-readable single-request timeline (what ``obs req`` prints)."""
    if not evs:
        return f"request {request_id}: no events found"
    t0 = evs[0].get("ts", 0.0)
    lines = [f"request {request_id}  ({len(evs)} events)"]
    for e in evs:
        rel = (e.get("ts", t0) - t0) * 1e3
        extras = {
            k: v
            for k, v in e.items()
            if k not in ("ts", "seq", "type", "request_id", "pid", "node")
        }
        where = e.get("node", "")[:8] or e.get("pid", "")
        detail = " ".join(f"{k}={v}" for k, v in extras.items())
        lines.append(f"  +{rel:9.1f}ms  {e.get('type', '?'):<24} {detail}  [{where}]")
    # summary: TTFT / decode steps / acceptance / finish
    ttft = next((e["ttft_s"] for e in evs if e.get("type") == "llm.first_token"), None)
    fin = next((e for e in evs if e.get("type") == "llm.finish"), None)
    verifies = [e for e in evs if e.get("type") == "llm.verify"]
    parts = []
    if ttft is not None:
        parts.append(f"ttft={_fmt_ms(ttft)}")
    if verifies:
        acc = sum(e.get("accepted", 0) for e in verifies)
        prop = sum(e.get("proposed", 0) for e in verifies)
        parts.append(
            f"spec: {len(verifies)} windows accepted {acc}/{prop} "
            f"({acc / max(prop, 1):.2f})"
        )
    if fin:
        parts.append(
            f"finished: {fin.get('reason')} after {fin.get('tokens_out')} tokens "
            f"in {_fmt_ms(fin.get('dur_s', 0.0))}"
        )
    if parts:
        lines.append("  -- " + "  ".join(parts))
    # phase lane: the request's own latency decomposition (one ledger
    # fold per engine attempt; attribute_rows joins it with the proxy
    # anchors for the cross-process legs)
    rows = attribute_rows(evs)
    for row in rows:
        lane = "  ".join(
            f"{k}={_fmt_ms(v)}"
            for k, v in row["phases"].items()
            if v > 0
        )
        lines.append(
            f"  -- phases ({row['scope']}, e2e={_fmt_ms(row['e2e'])}"
            + (", resumed" if row["resumed"] else "")
            + f"): {lane}"
        )
    return "\n".join(lines)


def cmd_req(args) -> int:
    if _offline(args):
        evs = [
            r for r in _load_crash_files(args.events_dir)
            if r.get("request_id") == args.request_id
        ]
        evs.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
        print(render_request(args.request_id, evs))
        return 0 if evs else 1
    ray_tpu = _attach(args.address)
    try:
        evs = request_events(args.request_id, args.events_dir)
        print(render_request(args.request_id, evs))
        return 0 if evs else 1
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# attribute: per-request phase decomposition + fleet critical-path report
# ---------------------------------------------------------------------------


def attribute_rows(evs: list[dict]) -> list[dict]:
    """Join the phase-plane events (``llm.phase.ledger`` from the engine,
    ``llm.phase.proxy`` from the HTTP proxy) into one decomposition per
    request.  The join is pure anchor arithmetic and telescopes exactly:
    ``proxy + dispatch|failover + Σ(engine phases) + stream == t_done −
    t_recv`` (engine-only rows: ``Σ(engine phases) == t_finish −
    t_submit`` — the by-construction cursor identity).  A resumed
    request's surviving ledger covers only the second attempt; the gap
    back to the proxy's dispatch anchor — the dead attempt plus the
    re-dispatch — is reported as ``failover``, never re-counted into
    token phases."""
    from ray_tpu.util import phases as ph

    ledgers: dict = {}
    proxies: dict = {}
    for e in evs:
        rid = e.get("request_id")
        if not rid:
            continue
        t = e.get("type")
        if t == "llm.phase.ledger":
            cur = ledgers.get(rid)
            # keep the newest fold: after a mid-stream failover only the
            # surviving attempt's ledger describes delivered work
            if cur is None or e.get("t_finish", 0.0) >= cur.get("t_finish", 0.0):
                ledgers[rid] = e
        elif t == "llm.phase.proxy":
            proxies[rid] = e
    order = [name for name, _o, _d in ph.PHASES]
    rows = []
    for rid, led in sorted(ledgers.items()):
        eng = led.get("phases") or {}
        phases = {k: float(eng.get(k, 0.0)) for k in ph.ENGINE_PHASES}
        row = {
            "request_id": rid,
            "resumed": bool(led.get("resumed")),
            "reason": led.get("reason"),
        }
        t_submit = led.get("t_submit", 0.0)
        t_finish = led.get("t_finish", 0.0)
        prox = proxies.get(rid)
        if prox is not None and prox.get("t_dispatch") is not None:
            t_recv, t_done = prox["t_recv"], prox["t_done"]
            t_disp = prox["t_dispatch"]
            phases["proxy"] = max(0.0, t_disp - t_recv)
            if row["resumed"]:
                phases["failover"] = max(0.0, t_submit - t_disp)
            else:
                phases["dispatch"] = max(0.0, t_submit - t_disp)
            phases["stream"] = max(0.0, t_done - t_finish)
            row["e2e"] = max(0.0, t_done - t_recv)
            row["scope"] = "proxy"
        else:
            row["e2e"] = max(0.0, t_finish - t_submit)
            row["scope"] = "engine"
        row["phases"] = {
            k: round(phases[k], 6) for k in order if phases.get(k)
        }
        s = sum(phases.values())
        row["phase_sum"] = round(s, 6)
        row["err"] = (
            abs(s - row["e2e"]) / row["e2e"] if row["e2e"] > 0 else 0.0
        )
        row["dominant"] = (
            max(row["phases"], key=row["phases"].get) if row["phases"] else None
        )
        rows.append(row)
    return rows


def _pcts_of(vals: list[float]) -> dict:
    vals = sorted(vals)
    n = len(vals)

    def q(p: float):
        return vals[min(n - 1, int(round(p * (n - 1))))] if n else None

    return {
        "count": n,
        "p50": q(0.50),
        "p95": q(0.95),
        "p99": q(0.99),
        "mean": (sum(vals) / n) if n else None,
    }


def attribution_report(
    rows: list[dict], top: int = 10, eps: float = 0.05
) -> dict:
    """Fleet-level critical-path report over per-request decompositions:
    per-phase p50/p95/p99, the top-N slowest requests with their dominant
    phase, and the p99-budget identity — the fraction of requests whose
    phases sum to measured end-to-end within ``eps`` (the acceptance
    gate loadgen and the CI smoke assert headlessly)."""
    from ray_tpu.util import phases as ph

    per_phase: dict = {}
    for r in rows:
        for k, v in r["phases"].items():
            per_phase.setdefault(k, []).append(v)
    order = [name for name, _o, _d in ph.PHASES]
    within = [r for r in rows if r["err"] <= eps]
    slowest = sorted(rows, key=lambda r: -r["e2e"])[:top]
    e2e = _pcts_of([r["e2e"] for r in rows])
    return {
        "n_requests": len(rows),
        "eps": eps,
        "within_eps": len(within),
        "within_eps_frac": (len(within) / len(rows)) if rows else None,
        "worst_err": max((r["err"] for r in rows), default=None),
        "scopes": {
            s: sum(1 for r in rows if r["scope"] == s)
            for s in ("proxy", "engine")
        },
        "resumed": sum(1 for r in rows if r["resumed"]),
        "e2e": e2e,
        "per_phase": {
            k: _pcts_of(per_phase[k]) for k in order if k in per_phase
        },
        "slowest": [
            {
                "request_id": r["request_id"],
                "e2e": round(r["e2e"], 6),
                "dominant": r["dominant"],
                "dominant_s": round(
                    r["phases"].get(r["dominant"], 0.0), 6
                ) if r["dominant"] else 0.0,
                "resumed": r["resumed"],
                "reason": r["reason"],
            }
            for r in slowest
        ],
    }


def render_attribution(report: dict) -> str:
    """The ``obs attribute`` tables: per-phase percentiles (below-2-samples
    ``—`` contract), the p99 budget line, and the slowest requests."""
    n = report["n_requests"]
    if not n:
        return "no phase ledgers found (no llm.phase.* events — is the " \
               "engine serving with RAY_TPU_PHASES enabled?)"
    lines = [
        f"request phase attribution: {n} requests "
        f"(proxy-joined={report['scopes']['proxy']} "
        f"engine-only={report['scopes']['engine']} "
        f"resumed={report['resumed']})",
        f"{'PHASE':<12} {'N':>6}  {'P50':>9} {'P95':>9} {'P99':>9}",
    ]
    for name, p in report["per_phase"].items():
        if p.get("count", 0) < 2:
            lines.append(f"{name:<12} {p.get('count', 0):>6}  "
                         f"{'—':>9} {'—':>9} {'—':>9}")
            continue
        lines.append(
            f"{name:<12} {p['count']:>6}  {_fmt_us(p['p50']):>9} "
            f"{_fmt_us(p['p95']):>9} {_fmt_us(p['p99']):>9}"
        )
    e2e = report["e2e"]
    lines.append(
        f"{'e2e':<12} {e2e['count']:>6}  "
        + (
            f"{_fmt_us(e2e['p50']):>9} {_fmt_us(e2e['p95']):>9} "
            f"{_fmt_us(e2e['p99']):>9}"
            if e2e.get("count", 0) >= 2
            else f"{'—':>9} {'—':>9} {'—':>9}"
        )
    )
    frac = report["within_eps_frac"]
    lines.append(
        f"p99 budget: phases sum to e2e within ε={report['eps']:.0%} for "
        f"{report['within_eps']}/{n} requests ({frac:.1%})"
        + (
            f", worst err {report['worst_err']:.2%}"
            if report.get("worst_err") is not None
            else ""
        )
    )
    if report["slowest"]:
        lines.append(f"{'SLOWEST':<28} {'E2E':>9}  DOMINANT")
        for s in report["slowest"]:
            lines.append(
                f"{s['request_id'][:26]:<28} {_fmt_us(s['e2e']):>9}  "
                f"{s['dominant']}={_fmt_us(s['dominant_s'])}"
                + (" (resumed)" if s["resumed"] else "")
                + (f" [{s['reason']}]" if s.get("reason") else "")
            )
    return "\n".join(lines)


def cmd_attribute(args) -> int:
    from ray_tpu._private import events as ev

    ray_tpu = None
    if not _offline(args):
        ray_tpu = _attach(args.address)
    try:
        evs = ev.collect_cluster_events() if ray_tpu is not None else []
        evs.extend(_load_crash_files(args.events_dir))
        evs = _dedup(evs)
        rows = attribute_rows(evs)
        report = attribution_report(rows, top=args.top, eps=args.eps)
        if args.output:
            with open(args.output, "w") as fh:
                json.dump({"report": report, "rows": rows}, fh, default=repr)
        if args.json:
            print(json.dumps(report, default=repr))
        else:
            print(render_attribution(report))
        return 0 if rows else 1
    finally:
        if ray_tpu is not None:
            ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# events / timeline
# ---------------------------------------------------------------------------


def cmd_events(args) -> int:
    from ray_tpu._private import events as ev

    ray_tpu = None
    if not _offline(args):
        ray_tpu = _attach(args.address)
    try:
        evs = (
            ev.collect_cluster_events(args.request_id or None)
            if ray_tpu is not None
            else []
        )
        evs.extend(
            rec
            for rec in _load_crash_files(args.events_dir)
            if not args.request_id or rec.get("request_id") == args.request_id
        )
        if args.type:
            evs = [e for e in evs if str(e.get("type", "")).startswith(args.type)]
        evs.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
        evs = _dedup(evs)
        for e in evs[-args.tail :]:
            print(json.dumps(e, default=repr))
        return 0
    finally:
        if ray_tpu is not None:
            ray_tpu.shutdown()


def offline_trace(events_dir: Optional[str], output: str) -> list[dict]:
    """Chrome trace from crash-flush JSONL alone — no cluster needed.
    The postmortem path (CI artifacts, a dead cluster's events dir):
    every flushed event becomes an instant marker on its process's lane,
    and request-tagged events additionally get their per-request lane."""
    from ray_tpu.util import tracing

    evs = _load_crash_files(events_dir)
    entries = []
    for e in evs:
        args = {
            k: v
            for k, v in e.items()
            if k not in ("ts", "type", "seq", "pid", "crash_flush")
        }
        entries.append(
            {
                "name": e.get("type", "event"),
                "cat": "recorder",
                "ph": "i",
                "s": "t",
                "ts": e.get("ts", 0.0) * 1e6,
                "pid": f"proc-{e.get('pid', '?')}",
                "tid": e.get("crash_flush", "events"),
                "args": args,
            }
        )
    entries += tracing.request_lanes([], evs)
    with open(output, "w") as f:
        json.dump(entries, f)
    return entries


def cmd_timeline(args) -> int:
    from ray_tpu.util import tracing

    if args.events_dir:
        events = offline_trace(args.events_dir, args.output)
        lanes = {e["tid"] for e in events if e.get("pid") == "requests"}
        print(
            f"wrote {len(events)} events ({len(lanes)} request lanes) "
            f"to {args.output} (offline, from {args.events_dir})"
        )
        return 0
    ray_tpu = _attach(args.address)
    try:
        events = tracing.export_chrome_trace(args.output)
        lanes = {e["tid"] for e in events if e.get("pid") == "requests"}
        print(
            f"wrote {len(events)} events ({len(lanes)} request lanes) "
            f"to {args.output}"
        )
        return 0
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# objects / arena: the object-plane flight deck (ISSUE 19)
# ---------------------------------------------------------------------------


def render_objects(ledger: dict, sort: str = "size", top: int = 0) -> str:
    """The ``obs objects`` table: directory rows (already size-sorted by
    the head; re-sorted here for ``--sort age``), the poisoned refs folded
    from worker reports, the freed-forensics tail, and the summary."""
    rows = list(ledger.get("objects", ()))
    if sort == "age":
        rows.sort(key=lambda r: r.get("age_s") or 0.0, reverse=True)
    if top:
        rows = rows[:top]
    s = ledger.get("summary", {})
    by_state = s.get("by_state") or {}
    lines = [
        f"object ledger: {s.get('objects', 0)} objects, "
        f"{_fmt_bytes(s.get('bytes', 0))}  "
        + " ".join(f"{k}={v}" for k, v in sorted(by_state.items())),
        f"{'OBJECT':<18} {'STATE':<9} {'NODE':<10} {'SIZE':>9} "
        f"{'REFS':>5} {'PINS':>5} {'AGE':>8}  LOCATION",
    ]
    for r in rows:
        loc = r.get("spill_path") or r.get("seg") or "-"
        flag = " !err" if r.get("is_error") else ""
        lines.append(
            f"{r['object_id'][:16]:<18} {r['state']:<9} "
            f"{str(r['node'])[:10]:<10} {_fmt_bytes(r['size']):>9} "
            f"{r.get('refcount', 0):>5} {r.get('pins', 0):>5} "
            f"{r.get('age_s', 0.0):>7.1f}s  {loc}{flag}"
        )
    if not rows:
        lines.append("(no live objects match)")
    for p in ledger.get("poisoned", ()):
        lines.append(
            f"{p['object_id'][:16]:<18} {'poisoned':<9} "
            f"{str(p.get('node', '-'))[:10]:<10} {'-':>9} {'-':>5} {'-':>5} "
            f"{'-':>8}  pid={p.get('pid')}"
        )
    freed = ledger.get("freed") or []
    if freed:
        lines.append(f"recently freed ({len(freed)}):")
        for f in freed[-5:]:
            lines.append(
                f"  {f['object_id'][:16]} {_fmt_bytes(f['size'])} "
                f"lived {f['age_s']:.1f}s ({f['reason']})"
            )
    return "\n".join(lines)


def render_audit(audit: dict) -> str:
    """The ``obs objects --audit`` leak report: one line per finding with
    node/object provenance, or the clean bill with coverage counts."""
    checked = audit.get("checked", {})
    coverage = (
        f"checked {checked.get('objects', 0)} objects, "
        f"{checked.get('owned_allocations', 0)} allocations, "
        f"{checked.get('spill_files', 0)} spill files, "
        f"{checked.get('pins', 0)} pins "
        f"(pin lease {audit.get('pin_lease_s', 0):.0f}s)"
    )
    findings = audit.get("findings") or []
    if not findings:
        return f"object-plane audit: no leaks — {coverage}"
    lines = [f"object-plane audit: {len(findings)} finding(s) — {coverage}"]
    for f in findings:
        detail = " ".join(
            f"{k}={v}" for k, v in f.items() if k != "kind" and v is not None
        )
        lines.append(f"  LEAK {f['kind']}: {detail}")
    return "\n".join(lines)


def cmd_objects(args) -> int:
    from ray_tpu._private.runtime import get_ctx

    ray_tpu = _attach(args.address)
    try:
        ctx = get_ctx()
        # --sort age needs every row (the head's top-N cut is size-order)
        server_top = 0 if args.sort == "age" else args.top
        ledger = ctx.call(
            "object_ledger", top_n=server_top, node=args.node,
            state=args.state, timeout=args.timeout,
        )
        audit = (
            ctx.call("object_audit", timeout=args.timeout)
            if args.audit else None
        )
        doc = {"ledger": ledger}
        if audit is not None:
            doc["audit"] = audit
        if args.output:
            with open(args.output, "w") as fh:
                json.dump(doc, fh, default=repr)
        if args.json:
            print(json.dumps(doc, default=repr))
        else:
            print(render_objects(ledger, sort=args.sort, top=args.top))
            if audit is not None:
                print()
                print(render_audit(audit))
        return 1 if (audit is not None and audit.get("findings")) else 0
    finally:
        ray_tpu.shutdown()


def _bar(frac: float, width: int = 30, mark: float = 0.9) -> str:
    """Occupancy bar with the degrade watermark marked: ``####..|...``."""
    frac = max(0.0, min(1.0, frac))
    fill = int(frac * width)
    cells = ["#" if i < fill else "." for i in range(width)]
    m = int(mark * width)
    if 0 <= m < width and cells[m] == ".":
        cells[m] = "|"
    return "".join(cells)


def render_arena(nodes: dict) -> str:
    """The ``obs arena`` per-node residency view: occupancy against
    capacity (watermark at the 90% degrade threshold data_plane puts
    honor), pinned bytes/count, oldest pin age, and spilled bytes."""
    if not nodes:
        return "no object-plane residency reported"
    lines = []
    for tag in sorted(nodes):
        s = nodes[tag] or {}
        used = s.get("used") or 0
        cap = s.get("capacity") or 0
        frac = (used / cap) if cap else 0.0
        pin_age = s.get("oldest_pin_age_s") or 0.0
        lines.append(
            f"{str(tag)[:12]:<12} [{_bar(frac)}] {frac:>4.0%} "
            f"{_fmt_bytes(used)}/{_fmt_bytes(cap)}  "
            f"pinned={_fmt_bytes(s.get('pinned_bytes') or 0)}"
            f"({s.get('pins') or 0})"
            + (f" oldest-pin={pin_age:.0f}s" if pin_age else "")
            + (
                f" spilled={_fmt_bytes(s['spill_bytes'])}"
                if s.get("spill_bytes") else ""
            )
        )
    return "\n".join(lines)


def cmd_arena(args) -> int:
    from ray_tpu._private.runtime import get_ctx

    ray_tpu = _attach(args.address)
    try:
        ledger = get_ctx().call(
            "object_ledger", top_n=1, timeout=args.timeout
        )
        nodes = ledger.get("nodes", {})
        if args.json:
            print(json.dumps(nodes, default=repr))
        else:
            print(render_arena(nodes))
        return 0
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.obs",
        description="live cluster / request observability",
    )
    parser.add_argument("--address", default=None, help="head HOST:PORT")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("top", help="live cluster + LLM engine view")
    p.add_argument("--watch", type=float, default=2.0, help="refresh seconds")
    p.add_argument("--once", action="store_true", help="print one frame and exit")
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser("req", help="one request's timeline")
    p.add_argument("request_id")
    p.add_argument("--events-dir", default=None, help="crash-flush JSONL dir")
    p.set_defaults(fn=cmd_req)

    p = sub.add_parser(
        "attribute",
        help="per-request phase decomposition + fleet p50/p95/p99 "
        "critical-path report (joins llm.phase.* events across processes)",
    )
    p.add_argument("--top", type=int, default=10,
                   help="slowest-requests rows to show")
    p.add_argument("--eps", type=float, default=0.05,
                   help="phase-sum identity tolerance (fraction of e2e)")
    p.add_argument("--events-dir", default=None,
                   help="also read crash-flush JSONL (offline with no address)")
    p.add_argument("--json", action="store_true")
    p.add_argument("-o", "--output", default=None,
                   help="write the full report + per-request rows JSON")
    p.set_defaults(fn=cmd_attribute)

    p = sub.add_parser("events", help="tail the cluster flight recorder")
    p.add_argument("--tail", type=int, default=50)
    p.add_argument("--type", default=None, help="event-type prefix filter")
    p.add_argument("--request-id", default=None)
    p.add_argument("--events-dir", default=None, help="crash-flush JSONL dir")
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("timeline", help="export a chrome trace with request lanes")
    p.add_argument("-o", "--output", default="ray_tpu_trace.json")
    p.add_argument(
        "--events-dir", default=None,
        help="build the trace offline from crash-flush JSONL (no cluster)",
    )
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("series", help="metric time-series history (sparklines)")
    p.add_argument("metric", nargs="?", default=None, help="metric name (all if omitted)")
    p.add_argument("--window", type=float, default=60.0,
                   help="percentile window seconds (histograms)")
    p.set_defaults(fn=cmd_series)

    p = sub.add_parser("alerts", help="SLO rule engine state (burn-rate alerts)")
    p.add_argument("--eval-once", action="store_true",
                   help="force one evaluation pass before reporting (headless/CI)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_alerts)

    p = sub.add_parser(
        "waterfall",
        help="task-hop phase breakdown (submit→…→reply p50/p95/p99 "
        "from the head's folded histograms)",
    )
    p.add_argument("--probe", type=int, default=0,
                   help="first drive N sync noop tasks under a traced "
                   "context (fresh clusters have no folded data)")
    p.add_argument("--recent", type=int, default=0,
                   help="also print the newest N raw stamp records")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_waterfall)

    p = sub.add_parser(
        "overhead",
        help="self-measure telemetry emit-path cost (ns/event, "
        "ns/unsampled-context, ns/counter-inc) — no cluster needed",
    )
    p.add_argument("-n", type=int, default=200_000, help="iterations per probe")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_overhead)

    p = sub.add_parser(
        "objects",
        help="object-plane ledger: states, sizes, ages; --audit hunts leaks",
    )
    p.add_argument("--top", type=int, default=20,
                   help="row cap after filters (0 = all)")
    p.add_argument("--sort", choices=("size", "age"), default="size")
    p.add_argument("--node", default=None, help="owner-node hex filter")
    p.add_argument("--state", default=None,
                   help="state filter (inline/arena/segment/spilled/poisoned)")
    p.add_argument("--audit", action="store_true",
                   help="run the cluster leak audit; exit non-zero on findings")
    p.add_argument("--timeout", type=float, default=2.0,
                   help="worker report rendezvous deadline seconds")
    p.add_argument("--json", action="store_true")
    p.add_argument("-o", "--output", default=None,
                   help="also write the ledger (+audit) JSON to a file")
    p.set_defaults(fn=cmd_objects)

    p = sub.add_parser(
        "arena",
        help="per-node arena occupancy/watermark/pin bars",
    )
    p.add_argument("--timeout", type=float, default=2.0,
                   help="worker report rendezvous deadline seconds")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_arena)

    p = sub.add_parser(
        "export", help="OTLP-JSON export of spans + events + metric series"
    )
    p.add_argument("-o", "--output", default="ray_tpu_otlp.json")
    p.add_argument("--otlp", action="store_true",
                   help="(default) OTLP JSON — flag kept for explicitness")
    p.add_argument("--events-dir", default=None,
                   help="offline: export crash-flush JSONL only (no cluster)")
    p.add_argument("--post", action="store_true",
                   help="also POST to RAY_TPU_OTLP_ENDPOINT")
    p.set_defaults(fn=cmd_export)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
