"""Lazy task DAGs (reference: ``python/ray/dag/dag_node.py``,
``input_node.py``, ``output_node.py``).

``f.bind(x)`` builds a DAG node; ``node.execute(input)`` walks the graph
submitting tasks with upstream ObjectRefs as args. Each ``execute`` call
evaluates every node exactly ONCE (diamond-shaped graphs don't double-submit)
and threads the runtime input through ``InputNode`` placeholders::

    with InputNode() as inp:
        a = preprocess.bind(inp)
        dag = combine.bind(a, postprocess.bind(a))
    ray_tpu.get(dag.execute(x))

Compiled (accelerated) DAG execution over reusable channels is a later-round
feature; this module provides the lazy-graph surface.
"""

from __future__ import annotations

from typing import Any


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- per-execution walk (memo: id(node) -> result) ---------------------

    def _resolve(self, v: Any, memo: dict):
        if isinstance(v, DAGNode):
            return v._execute_memo(memo)
        return v

    def _resolved_args(self, memo: dict):
        args = [self._resolve(a, memo) for a in self._bound_args]
        kwargs = {k: self._resolve(v, memo) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_memo(self, memo: dict):
        key = id(self)
        if key not in memo:
            memo[key] = self._execute_impl(memo)
        return memo[key]

    def _execute_impl(self, memo: dict):
        raise NotImplementedError

    def execute(self, *input_args):
        """Evaluate the graph. ``input_args`` feed the graph's InputNode(s):
        one positional value per distinct InputNode, in first-use order (the
        common case is a single InputNode)."""
        if isinstance(self, InputNode):
            raise RuntimeError(
                "InputNode has no value — call dag.execute(input_value) on a "
                "downstream node instead of executing the InputNode itself"
            )
        collected = self._collect_inputs()
        if len(input_args) != len(collected):
            raise ValueError(
                f"dag has {len(collected)} InputNode(s) but execute() got "
                f"{len(input_args)} argument(s)"
            )
        memo: dict = {
            id(node): value for node, value in zip(collected, input_args)
        }
        return self._execute_memo(memo)

    def experimental_compile(self, buffer_size_bytes: int = 1 << 20):
        """Compile this DAG for channel-based repeated execution
        (reference: ``dag_node.py:108`` experimental_compile)."""
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, buffer_size_bytes)

    def _collect_inputs(self) -> list["InputNode"]:
        inputs: list = []
        visited: set[int] = set()  # diamonds: walk each node once

        def walk(node):
            if not isinstance(node, DAGNode) or id(node) in visited:
                return
            visited.add(id(node))
            if isinstance(node, InputNode):
                inputs.append(node)
            for v in list(node._bound_args) + list(node._bound_kwargs.values()):
                walk(v)

        walk(self)
        return inputs


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def _execute_impl(self, memo: dict):
        args, kwargs = self._resolved_args(memo)
        return self._fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._cls = actor_cls

    def _execute_impl(self, memo: dict):
        args, kwargs = self._resolved_args(memo)
        return self._cls.remote(*args, **kwargs)


class InputNode(DAGNode):
    """Placeholder for runtime input (reference: ``dag/input_node.py``);
    ``execute(x)`` on any downstream node substitutes ``x`` here."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, memo: dict):
        raise RuntimeError(
            "InputNode has no value — call dag.execute(input_value) on a "
            "downstream node instead of executing the InputNode itself"
        )


class MultiOutputNode(DAGNode):
    """Bundle several leaves into one execute() (reference:
    ``dag/output_node.py``). ``execute`` returns a list of refs."""

    def __init__(self, outputs: list):
        super().__init__(tuple(outputs), {})

    def _execute_impl(self, memo: dict):
        args, _ = self._resolved_args(memo)
        return list(args)
