"""Lazy task DAGs (reference: ``python/ray/dag/dag_node.py`` + compiled DAGs).

``f.bind(x)`` builds a DAG node; ``node.execute()`` walks the graph
submitting tasks with upstream ObjectRefs as args. Compiled (accelerated)
DAG execution over reusable channels is a later-round feature; this module
provides the lazy-graph surface.
"""

from __future__ import annotations

from typing import Any


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _resolve(self, v: Any):
        if isinstance(v, DAGNode):
            return v.execute()
        return v

    def _resolved_args(self):
        args = [self._resolve(a) for a in self._bound_args]
        kwargs = {k: self._resolve(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def execute(self):
        raise NotImplementedError


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def execute(self):
        args, kwargs = self._resolved_args()
        return self._fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._cls = actor_cls

    def execute(self):
        args, kwargs = self._resolved_args()
        return self._cls.remote(*args, **kwargs)


class InputNode(DAGNode):
    """Placeholder for runtime input (reference: dag/input_node.py)."""

    def __init__(self):
        super().__init__((), {})
        self._value = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def execute(self):
        return self._value
