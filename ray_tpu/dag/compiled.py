"""Compiled (accelerated) DAG execution over reusable shm channels.

Reference: ``python/ray/dag/compiled_dag_node.py:141`` — compiling a DAG of
actor-method calls replaces per-call task submission with persistent
executors connected by mutable plasma channels. Same shape here, TPU-host
style: each participating actor runs one long-lived "exec loop" task that
blocks on its input :class:`~ray_tpu.experimental.channel.Channel`\\ s,
invokes the bound method, and pushes the result into its output channels.
After compile, ``execute(x)`` is: write x into the input-edge channels, read
the output-edge channel — no scheduler, no control-plane round-trips.

Restrictions (matching the reference's early accelerated-DAG rules):
every non-input node is an actor-method call, each actor appears in at most
one node, and values must fit the channel capacity.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.dag import DAGNode, InputNode, MultiOutputNode
from ray_tpu.experimental.channel import Channel, ChannelClosed


class ClassMethodNode(DAGNode):
    """``actor.method.bind(...)`` — an actor-method call site in a DAG."""

    def __init__(self, handle, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._handle = handle
        self._method_name = method_name

    def _execute_impl(self, memo: dict):
        args, kwargs = self._resolved_args(memo)
        return getattr(self._handle, self._method_name).remote(*args, **kwargs)

    def experimental_compile(self, buffer_size_bytes: int = 1 << 20) -> "CompiledDAG":
        return CompiledDAG(self, buffer_size_bytes)


class CompiledDAGRef:
    """Result handle for one compiled execution (reference:
    CompiledDAGRef) — ``get()`` reads the output channel."""

    def __init__(self, channels: list[Channel], single: bool):
        self._channels = channels
        self._single = single
        self._consumed = False

    def get(self, timeout: Optional[float] = 30.0):
        if self._consumed:
            raise ValueError("CompiledDAGRef already consumed")
        self._consumed = True
        vals = [c.read(timeout=timeout) for c in self._channels]
        for v in vals:
            if isinstance(v, _WrappedError):
                raise v.error
        return vals[0] if self._single else vals


class _WrappedError:
    """Marks an executor-side exception traveling through a channel."""

    def __init__(self, error: BaseException):
        self.error = error


class CompiledDAG:
    def __init__(self, root: DAGNode, buffer_size_bytes: int = 1 << 20):
        self._buffer = buffer_size_bytes
        self._torn_down = False
        outputs = (
            list(root._bound_args) if isinstance(root, MultiOutputNode) else [root]
        )
        self._single_output = not isinstance(root, MultiOutputNode)

        # topo-walk: collect nodes, validate shape
        order: list[DAGNode] = []
        seen: set[int] = set()

        def walk(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for dep in list(node._bound_args) + list(node._bound_kwargs.values()):
                if isinstance(dep, DAGNode):
                    walk(dep)
            order.append(node)

        for out in outputs:
            if not isinstance(out, DAGNode):
                raise ValueError("compiled DAG outputs must be DAG nodes")
            walk(out)

        self._inputs = [n for n in order if isinstance(n, InputNode)]
        self._nodes = [n for n in order if isinstance(n, ClassMethodNode)]
        if len(self._nodes) != len([n for n in order if not isinstance(n, InputNode)]):
            raise ValueError(
                "compiled DAGs support actor-method nodes only "
                "(bind methods on actor handles; plain task nodes cannot hold "
                "a persistent executor)"
            )
        actors = [n._handle._actor_id for n in self._nodes]
        if len(set(actors)) != len(actors):
            raise ValueError("each actor may appear at most once in a compiled DAG")

        # one channel per EDGE OCCURRENCE (the same producer appearing twice
        # in one arg list gets two channels, one per position)
        self._input_edges: dict[int, list[Channel]] = {id(n): [] for n in self._inputs}
        self._output_channels: list[Channel] = []
        out_edges: dict[int, list[Channel]] = {}  # id(producer) -> channels
        all_edges: list[Channel] = []

        def make_edge(src: DAGNode) -> Channel:
            ch = Channel(self._buffer)
            out_edges.setdefault(id(src), []).append(ch)
            all_edges.append(ch)
            return ch

        plans = []
        for node in self._nodes:
            in_specs = []
            for dep in list(node._bound_args):
                if isinstance(dep, InputNode):
                    ch = make_edge(dep)
                    self._input_edges[id(dep)].append(ch)
                    in_specs.append(("chan", ch))
                elif isinstance(dep, ClassMethodNode):
                    in_specs.append(("chan", make_edge(dep)))
                elif isinstance(dep, DAGNode):
                    raise ValueError(f"unsupported node type in compiled DAG: {dep!r}")
                else:
                    in_specs.append(("const", dep))
            if node._bound_kwargs:
                raise ValueError("compiled DAGs support positional args only")
            plans.append({"node": node, "in": in_specs, "out": []})

        by_id = {id(p["node"]): p for p in plans}
        for src_id, chans in out_edges.items():
            p = by_id.get(src_id)
            if p is not None:
                p["out"].extend(chans)
        for out_node in outputs:
            ch = Channel(self._buffer)
            by_id[id(out_node)]["out"].append(ch)
            self._output_channels.append(ch)

        # launch one persistent exec-loop task per actor (the actor's
        # dispatch queue is owned by the loop until teardown, like the
        # reference's compiled-DAG executors)
        self._loop_refs = []
        for p in plans:
            node = p["node"]
            self._loop_refs.append(
                node._handle.__dag_exec__.remote(node._method_name, p["in"], p["out"])
            )
        self._all_channels = all_edges + self._output_channels

    def execute(self, *args) -> CompiledDAGRef:
        if self._torn_down:
            raise ValueError("compiled DAG was torn down")
        if len(args) != len(self._inputs):
            raise ValueError(
                f"dag has {len(self._inputs)} InputNode(s), got {len(args)} args"
            )
        for node, value in zip(self._inputs, args):
            for ch in self._input_edges[id(node)]:
                ch.write(value, timeout=30.0)
        return CompiledDAGRef(self._output_channels, self._single_output)

    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self._all_channels:
            ch.close()
        import ray_tpu

        for ref in self._loop_refs:
            try:
                ray_tpu.get(ref, timeout=10)
            except Exception as e:
                from ray_tpu._private.log_util import warn_throttled

                # expected when an exec-loop actor died mid-DAG, but a
                # teardown that ALWAYS fails here means loops leaking
                warn_throttled("compiled dag: exec-loop join", e)
        for ch in self._all_channels:
            ch.destroy()

    def __del__(self):
        # GC-safe: teardown blocks in ray_tpu.get — never allowed from a GC
        # tick (could fire in a thread holding the head lock). Hand the whole
        # teardown to the context's gc-drain thread; resurrecting self via
        # the bound method is fine (PEP 442: __del__ runs at most once).
        if self._torn_down:
            return
        try:
            from ray_tpu._private.runtime import _ctx

            if _ctx is not None and not _ctx.closed:
                _ctx.enqueue_gc("thunk", self.teardown)
                return
        except Exception:
            pass
        # no live context: skip the blocking exec-loop join but still unlink
        # the channels' shm segments (destroy needs no runtime) — GC-safe
        # because channel close/destroy touch no head or connection locks
        self._torn_down = True
        for ch in self._all_channels:
            try:
                ch.close()
                ch.destroy()
            except Exception as e:
                try:
                    from ray_tpu._private.log_util import warn_throttled

                    warn_throttled("compiled dag: channel teardown", e)
                except Exception:  # raylint: disable=RL007
                    pass  # interpreter teardown: even logging can fail
