"""Cluster dashboard: REST API + a small live HTML overview.

TPU-native counterpart of the reference's dashboard head
(``dashboard/head.py`` — an aiohttp REST server with per-module routes —
plus the React frontend in ``dashboard/client/``). Re-designed for this
runtime: cluster state already lives in the driver-attached head, so the
dashboard is a stdlib ``ThreadingHTTPServer`` thread inside any attached
process — no separate daemon, no node agents, no build step. Endpoints
mirror the reference's REST surface (nodes/actors/tasks/jobs/metrics) and
``/metrics`` serves Prometheus text like the metrics agent
(``dashboard/modules/reporter/reporter_agent.py``).

Usage::

    ray_tpu.init()
    url = ray_tpu.dashboard.start()     # -> http://127.0.0.1:8265
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None


def _payload(path: str):
    import ray_tpu
    from ray_tpu.util import metrics as um
    from ray_tpu.util import state as st

    from urllib.parse import parse_qs, urlsplit

    parts = urlsplit(path)
    path, query = parts.path.rstrip("/"), parse_qs(parts.query)

    if path == "/api/version":
        return {"ray_tpu": getattr(ray_tpu, "__version__", "dev"), "dashboard": 1}
    if path == "/api/nodes":
        return st.list_nodes()
    if path == "/api/actors":
        return st.list_actors()
    if path == "/api/tasks":
        return st.list_tasks()
    if path == "/api/objects":
        return st.list_objects()
    if path == "/api/placement_groups":
        return st.list_placement_groups()
    if path == "/api/summary":
        return st.summary()
    if path == "/api/cluster_resources":
        return {
            "total": ray_tpu.cluster_resources(),
            "available": ray_tpu.available_resources(),
        }
    if path == "/api/node_stats":
        return st.get_node_stats()
    if path == "/api/worker_stacks":
        return st.get_worker_stacks()
    if path == "/api/profile":
        seconds = min(max(float(query.get("seconds", ["2"])[0]), 0.05), 60.0)
        return st.profile_workers(duration_s=seconds)
    if path == "/api/timeline":
        return st.timeline()
    if path == "/api/jobs":
        try:
            from ray_tpu.job import list_jobs

            return [j if isinstance(j, dict) else j.__dict__ for j in list_jobs()]
        except Exception:
            return []
    if path == "/api/logs":
        # job log tail (reference: dashboard log endpoints serve the
        # session log dir; here job supervisors capture entrypoint output)
        job_id = (query.get("job_id") or [""])[0]
        try:
            tail = int((query.get("tail") or ["2000"])[0])
        except ValueError:
            tail = 2000  # malformed client value: default, not a 500
        try:
            from ray_tpu.job import get_job_logs

            text = get_job_logs(job_id)
        except Exception as e:
            return {"job_id": job_id, "logs": f"(unavailable: {e})"}
        lines = (text or "").splitlines()
        return {"job_id": job_id, "logs": "\n".join(lines[-tail:])}
    if path == "/api/metrics":
        return um.collect()
    if path == "/api/percentiles":
        # p50/p95/p99 snapshots for every cluster histogram (obs top's
        # TTFT/ITL view over HTTP)
        return um.histogram_percentiles()
    if path == "/api/series":
        # merged metric time series (?name= narrows to one metric) — the
        # data `obs series` renders, JSON for dashboards/tooling
        name = (query.get("name") or [None])[0]
        return um.collect_series(name)
    if path == "/api/alerts":
        # SLO burn-rate engine state (?eval=1 forces a pass first)
        return st.get_alerts(eval_now=(query.get("eval") or ["0"])[0] == "1")
    if path == "/api/events":
        # flight-recorder drain (cluster-wide, newest last); ?request_id=
        # narrows to one request, ?tail= caps the reply
        from ray_tpu._private import events as ev

        rid = (query.get("request_id") or [None])[0]
        try:
            tail = int((query.get("tail") or ["500"])[0])
        except ValueError:
            tail = 500
        return ev.collect_cluster_events(rid)[-tail:]
    if path == "/api/request":
        # one request's merged timeline (same data as `obs req <id>`)
        from ray_tpu.obs import request_events

        rid = (query.get("id") or [""])[0]
        if not rid:
            return {"error": "pass ?id=<request_id>"}
        return request_events(rid)
    if path == "/api/grafana":
        from ray_tpu.util.grafana import dashboard_json

        return dashboard_json()
    return None


# The SPA now lives in _dashboard_static/ (index.html / app.js /
# style.css) — hand-written, no build step; served by _Handler below.
_STATIC_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "_dashboard_static"
)
_STATIC = {
    "/": ("index.html", "text/html; charset=utf-8"),
    "/index.html": ("index.html", "text/html; charset=utf-8"),
    "/app.js": ("app.js", "text/javascript; charset=utf-8"),
    "/style.css": ("style.css", "text/css; charset=utf-8"),
}


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet by default
        pass

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        try:
            if self.path.split("?")[0] in _STATIC:
                fname, ctype = _STATIC[self.path.split("?")[0]]
                with open(os.path.join(_STATIC_DIR, fname), "rb") as f:
                    body = f.read()
            elif self.path == "/metrics":
                from ray_tpu.util import metrics as um

                body = um.prometheus_text().encode()
                ctype = "text/plain; version=0.0.4"
            else:
                data = _payload(self.path)
                if data is None:
                    self.send_error(404)
                    return
                body = json.dumps(data, default=str).encode()
                ctype = "application/json"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except BrokenPipeError:
            pass
        except Exception as e:  # surface handler bugs as 500s, not hangs
            try:
                self.send_error(500, str(e))
            except Exception:
                pass


def start(host: str = "127.0.0.1", port: Optional[int] = None) -> str:
    """Start the dashboard server (idempotent). Returns its URL.

    Default port comes from the ``dashboard_port`` config flag (8265, like
    the reference); ``port=0`` picks a free port (the URL reports it)."""
    if port is None:
        from ray_tpu._private.config import GLOBAL_CONFIG

        port = GLOBAL_CONFIG.dashboard_port
    global _server, _thread
    if _server is not None:
        h, p = _server.server_address[:2]
        return f"http://{h}:{p}"
    _server = ThreadingHTTPServer((host, port), _Handler)
    _server.daemon_threads = True
    _thread = threading.Thread(target=_server.serve_forever, name="dashboard", daemon=True)
    _thread.start()
    try:
        # live core series for /metrics + the generated Grafana board
        from ray_tpu.util.metrics import start_core_metrics

        start_core_metrics()
    except Exception:
        pass  # dashboard is usable without the sampler
    h, p = _server.server_address[:2]
    return f"http://{h}:{p}"


def stop() -> None:
    global _server, _thread
    if _server is not None:
        _server.shutdown()
        _server.server_close()
        _server = None
        _thread = None
