"""Cluster dashboard: REST API + a small live HTML overview.

TPU-native counterpart of the reference's dashboard head
(``dashboard/head.py`` — an aiohttp REST server with per-module routes —
plus the React frontend in ``dashboard/client/``). Re-designed for this
runtime: cluster state already lives in the driver-attached head, so the
dashboard is a stdlib ``ThreadingHTTPServer`` thread inside any attached
process — no separate daemon, no node agents, no build step. Endpoints
mirror the reference's REST surface (nodes/actors/tasks/jobs/metrics) and
``/metrics`` serves Prometheus text like the metrics agent
(``dashboard/modules/reporter/reporter_agent.py``).

Usage::

    ray_tpu.init()
    url = ray_tpu.dashboard.start()     # -> http://127.0.0.1:8265
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None


def _payload(path: str):
    import ray_tpu
    from ray_tpu.util import metrics as um
    from ray_tpu.util import state as st

    from urllib.parse import parse_qs, urlsplit

    parts = urlsplit(path)
    path, query = parts.path.rstrip("/"), parse_qs(parts.query)

    if path == "/api/version":
        return {"ray_tpu": getattr(ray_tpu, "__version__", "dev"), "dashboard": 1}
    if path == "/api/nodes":
        return st.list_nodes()
    if path == "/api/actors":
        return st.list_actors()
    if path == "/api/tasks":
        return st.list_tasks()
    if path == "/api/objects":
        return st.list_objects()
    if path == "/api/placement_groups":
        return st.list_placement_groups()
    if path == "/api/summary":
        return st.summary()
    if path == "/api/cluster_resources":
        return {
            "total": ray_tpu.cluster_resources(),
            "available": ray_tpu.available_resources(),
        }
    if path == "/api/node_stats":
        return st.get_node_stats()
    if path == "/api/worker_stacks":
        return st.get_worker_stacks()
    if path == "/api/profile":
        seconds = min(max(float(query.get("seconds", ["2"])[0]), 0.05), 60.0)
        return st.profile_workers(duration_s=seconds)
    if path == "/api/timeline":
        return st.timeline()
    if path == "/api/jobs":
        try:
            from ray_tpu.job import list_jobs

            return [j if isinstance(j, dict) else j.__dict__ for j in list_jobs()]
        except Exception:
            return []
    if path == "/api/metrics":
        return um.collect()
    return None


_INDEX = """<!doctype html><html><head><title>ray_tpu dashboard</title>
<meta http-equiv="refresh" content="5">
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;color:#222}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.5rem}
 table{border-collapse:collapse;min-width:30rem}
 td,th{border:1px solid #ccc;padding:.25rem .6rem;font-size:.85rem;text-align:left}
 th{background:#f3f3f3} code{background:#f6f6f6;padding:0 .2rem}
</style></head><body>
<h1>ray_tpu cluster</h1>
<div id="content">loading…</div>
<script>
async function j(u){return (await fetch(u)).json()}
function table(rows, cols){
 if(!rows.length) return "<i>none</i>";
 let h="<table><tr>"+cols.map(c=>`<th>${c}</th>`).join("")+"</tr>";
 for(const r of rows.slice(0,50))
   h+="<tr>"+cols.map(c=>`<td>${r[c]===undefined?"":JSON.stringify(r[c])}</td>`).join("")+"</tr>";
 return h+"</table>";
}
(async()=>{
 const res=await j("/api/cluster_resources"), nodes=await j("/api/nodes"),
   actors=await j("/api/actors"), summary=await j("/api/summary");
 document.getElementById("content").innerHTML =
  "<h2>Resources</h2><pre>"+JSON.stringify(res,null,1)+"</pre>"
  +"<h2>Nodes ("+nodes.length+")</h2>"+table(nodes,["node_id","alive","resources"])
  +"<h2>Actors ("+actors.length+")</h2>"+table(actors,["actor_id","class_name","state","name"])
  +"<h2>Task summary</h2><pre>"+JSON.stringify(summary.tasks||summary,null,1)+"</pre>";
})();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet by default
        pass

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        try:
            if self.path in ("/", "/index.html"):
                body = _INDEX.encode()
                ctype = "text/html; charset=utf-8"
            elif self.path == "/metrics":
                from ray_tpu.util import metrics as um

                body = um.prometheus_text().encode()
                ctype = "text/plain; version=0.0.4"
            else:
                data = _payload(self.path)
                if data is None:
                    self.send_error(404)
                    return
                body = json.dumps(data, default=str).encode()
                ctype = "application/json"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except BrokenPipeError:
            pass
        except Exception as e:  # surface handler bugs as 500s, not hangs
            try:
                self.send_error(500, str(e))
            except Exception:
                pass


def start(host: str = "127.0.0.1", port: Optional[int] = None) -> str:
    """Start the dashboard server (idempotent). Returns its URL.

    Default port comes from the ``dashboard_port`` config flag (8265, like
    the reference); ``port=0`` picks a free port (the URL reports it)."""
    if port is None:
        from ray_tpu._private.config import GLOBAL_CONFIG

        port = GLOBAL_CONFIG.dashboard_port
    global _server, _thread
    if _server is not None:
        h, p = _server.server_address[:2]
        return f"http://{h}:{p}"
    _server = ThreadingHTTPServer((host, port), _Handler)
    _server.daemon_threads = True
    _thread = threading.Thread(target=_server.serve_forever, name="dashboard", daemon=True)
    _thread.start()
    h, p = _server.server_address[:2]
    return f"http://{h}:{p}"


def stop() -> None:
    global _server, _thread
    if _server is not None:
        _server.shutdown()
        _server.server_close()
        _server = None
        _thread = None
