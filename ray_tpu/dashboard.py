"""Cluster dashboard: REST API + a small live HTML overview.

TPU-native counterpart of the reference's dashboard head
(``dashboard/head.py`` — an aiohttp REST server with per-module routes —
plus the React frontend in ``dashboard/client/``). Re-designed for this
runtime: cluster state already lives in the driver-attached head, so the
dashboard is a stdlib ``ThreadingHTTPServer`` thread inside any attached
process — no separate daemon, no node agents, no build step. Endpoints
mirror the reference's REST surface (nodes/actors/tasks/jobs/metrics) and
``/metrics`` serves Prometheus text like the metrics agent
(``dashboard/modules/reporter/reporter_agent.py``).

Usage::

    ray_tpu.init()
    url = ray_tpu.dashboard.start()     # -> http://127.0.0.1:8265
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None


def _payload(path: str):
    import ray_tpu
    from ray_tpu.util import metrics as um
    from ray_tpu.util import state as st

    from urllib.parse import parse_qs, urlsplit

    parts = urlsplit(path)
    path, query = parts.path.rstrip("/"), parse_qs(parts.query)

    if path == "/api/version":
        return {"ray_tpu": getattr(ray_tpu, "__version__", "dev"), "dashboard": 1}
    if path == "/api/nodes":
        return st.list_nodes()
    if path == "/api/actors":
        return st.list_actors()
    if path == "/api/tasks":
        return st.list_tasks()
    if path == "/api/objects":
        return st.list_objects()
    if path == "/api/placement_groups":
        return st.list_placement_groups()
    if path == "/api/summary":
        return st.summary()
    if path == "/api/cluster_resources":
        return {
            "total": ray_tpu.cluster_resources(),
            "available": ray_tpu.available_resources(),
        }
    if path == "/api/node_stats":
        return st.get_node_stats()
    if path == "/api/worker_stacks":
        return st.get_worker_stacks()
    if path == "/api/profile":
        seconds = min(max(float(query.get("seconds", ["2"])[0]), 0.05), 60.0)
        return st.profile_workers(duration_s=seconds)
    if path == "/api/timeline":
        return st.timeline()
    if path == "/api/jobs":
        try:
            from ray_tpu.job import list_jobs

            return [j if isinstance(j, dict) else j.__dict__ for j in list_jobs()]
        except Exception:
            return []
    if path == "/api/metrics":
        return um.collect()
    return None


_INDEX = """<!doctype html><html><head><title>ray_tpu dashboard</title>
<meta charset="utf-8"><meta name="viewport" content="width=device-width,initial-scale=1">
<style>
 :root{--ink:#1a1d21;--ink2:#5b6168;--line:#e3e6ea;--bg:#fafbfc;--card:#fff;
       --accent:#2f6fde;--accent-soft:#dbe7fb;--good:#2e7d32;--warn:#b26a00;--bad:#c62828}
 @media(prefers-color-scheme:dark){
  :root{--ink:#e7eaee;--ink2:#9aa1a9;--line:#32363c;--bg:#17191c;--card:#1f2226;
        --accent:#6b9ef2;--accent-soft:#26395c;--good:#7cc47f;--warn:#e0a84f;--bad:#ef8c8c}}
 body{font-family:system-ui,sans-serif;margin:0;color:var(--ink);background:var(--bg)}
 header{display:flex;align-items:baseline;gap:1rem;padding:.9rem 1.4rem;border-bottom:1px solid var(--line)}
 header h1{font-size:1.05rem;margin:0} header .sub{color:var(--ink2);font-size:.8rem}
 main{padding:1rem 1.4rem;max-width:72rem}
 .tiles{display:flex;flex-wrap:wrap;gap:.7rem;margin:.4rem 0 1rem}
 .tile{background:var(--card);border:1px solid var(--line);border-radius:8px;padding:.55rem .9rem;min-width:7.5rem}
 .tile .v{font-size:1.35rem;font-weight:600} .tile .k{color:var(--ink2);font-size:.72rem;text-transform:uppercase;letter-spacing:.04em}
 .meter{margin:.35rem 0}.meter .lbl{display:flex;justify-content:space-between;font-size:.8rem;color:var(--ink2)}
 .meter .bar{height:8px;background:var(--accent-soft);border-radius:4px;overflow:hidden;margin-top:2px}
 .meter .bar i{display:block;height:100%;background:var(--accent);border-radius:4px}
 nav{display:flex;gap:.15rem;margin:1rem 0 .6rem;border-bottom:1px solid var(--line)}
 nav button{border:0;background:none;color:var(--ink2);padding:.45rem .8rem;font-size:.85rem;cursor:pointer;border-bottom:2px solid transparent}
 nav button.on{color:var(--ink);border-color:var(--accent);font-weight:600}
 table{border-collapse:collapse;width:100%;background:var(--card);font-variant-numeric:tabular-nums}
 td,th{border:1px solid var(--line);padding:.3rem .6rem;font-size:.8rem;text-align:left;vertical-align:top}
 th{color:var(--ink2);font-weight:600;position:sticky;top:0;background:var(--card)}
 .st{display:inline-flex;align-items:center;gap:.3rem;font-size:.78rem}
 .st i{width:.55rem;height:.55rem;border-radius:50%;display:inline-block}
 pre{background:var(--card);border:1px solid var(--line);border-radius:6px;padding:.6rem;font-size:.75rem;overflow:auto}
 .muted{color:var(--ink2)} .counts span{margin-right:.9rem;font-size:.82rem}
</style></head><body>
<header><h1>ray_tpu</h1><span class="sub" id="meta">connecting…</span>
 <span style="flex:1"></span>
 <label class="sub"><input type="checkbox" id="auto" checked> auto-refresh</label></header>
<main>
 <div class="tiles" id="tiles"></div>
 <div id="meters"></div>
 <div class="counts" id="taskcounts"></div>
 <nav id="tabs"></nav>
 <div id="view">loading…</div>
</main>
<script>
const TABS=["nodes","actors","tasks","objects","placement_groups","jobs","metrics","worker_stacks"];
let tab="nodes";
const esc=s=>String(s).replace(/[&<>]/g,c=>({"&":"&amp;","<":"&lt;",">":"&gt;"}[c]));
const fmt=v=>v===undefined||v===null?"<span class=muted>—</span>":
 typeof v==="object"?"<code>"+esc(JSON.stringify(v))+"</code>":esc(v);
async function j(u){const r=await fetch(u);if(!r.ok)throw new Error(u+": "+r.status);return r.json()}
const STATE_COLOR={ALIVE:"var(--good)",RUNNING:"var(--accent)",PENDING:"var(--warn)",
 RESTARTING:"var(--warn)",DEAD:"var(--bad)",FAILED:"var(--bad)",FINISHED:"var(--ink2)",
 WAITING_DEPS:"var(--warn)",ASSIGNED:"var(--accent)"};
const stateCell=s=>`<span class=st><i style="background:${STATE_COLOR[s]||"var(--ink2)"}"></i>${esc(s)}</span>`;
function table(rows,cols,stateCol){
 if(!rows||!rows.length) return "<p class=muted>none</p>";
 let h="<table><tr>"+cols.map(c=>`<th>${esc(c)}</th>`).join("")+"</tr>";
 for(const r of rows.slice(0,200))
  h+="<tr>"+cols.map(c=>`<td>${c===stateCol?stateCell(r[c]):fmt(r[c])}</td>`).join("")+"</tr>";
 h+="</table>";
 if(rows.length>200)h+=`<p class=muted>…and ${rows.length-200} more</p>`;
 return h;
}
function meters(res){
 const tot=res.total||{},avail=res.available||{};
 return Object.keys(tot).filter(k=>k!=="memory").sort().map(k=>{
  const t=tot[k],u=t-(avail[k]??t),pct=t?Math.round(100*u/t):0;
  return `<div class=meter><span class=lbl><span>${esc(k)}</span><span>${+u.toFixed(2)} / ${+t.toFixed(2)} used</span></span>
   <span class=bar><i style="width:${pct}%"></i></span></div>`;}).join("");
}
const tile=(k,v)=>`<div class=tile><div class=v>${v}</div><div class=k>${esc(k)}</div></div>`;
async function render(){
 try{
  const [res,nodes,actors,summary]=await Promise.all([
   j("/api/cluster_resources"),j("/api/nodes"),j("/api/actors"),j("/api/summary")]);
  const tasks=(summary&&summary.tasks)||{};
  document.getElementById("meta").textContent=new Date().toLocaleTimeString();
  document.getElementById("tiles").innerHTML=
   tile("nodes",nodes.filter(n=>n.alive!==false).length)+
   tile("actors",actors.length)+
   tile("running tasks",tasks.RUNNING||0)+
   tile("pending tasks",(tasks.PENDING||0)+(tasks.WAITING_DEPS||0))+
   tile("objects",(summary&&summary.objects&&summary.objects.count)??"—");
  document.getElementById("meters").innerHTML=meters(res);
  document.getElementById("taskcounts").innerHTML=Object.entries(tasks)
   .map(([s,n])=>`<span>${stateCell(s)} ${n}</span>`).join("");
  document.getElementById("view").innerHTML=await view(tab,{nodes,actors});
 }catch(e){document.getElementById("view").innerHTML="<p class=muted>"+esc(e)+"</p>"}
}
async function view(t,pre){
 if(t==="nodes") return table(pre.nodes,["node_id","alive","resources","labels"],"");
 if(t==="actors") return table(pre.actors,["actor_id","class_name","name","state","node_id","restarts"],"state");
 if(t==="tasks") return table(await j("/api/tasks"),["task_id","name","state","kind","node_id"],"state");
 if(t==="objects") return table(await j("/api/objects"),["object_id","size","where","refcount","pins"],"");
 if(t==="placement_groups") return table(await j("/api/placement_groups"),["pg_id","state","strategy","bundles"],"state");
 if(t==="jobs") return table(await j("/api/jobs"),["job_id","status","entrypoint"],"status");
 if(t==="metrics") return "<pre>"+esc(JSON.stringify(await j("/api/metrics"),null,1))+"</pre>"+
   '<p class=muted>prometheus text at <a href="/metrics">/metrics</a></p>';
 if(t==="worker_stacks"){const s=await j("/api/worker_stacks");
  return Object.entries(s).map(([node,per])=>Object.entries(per).map(([pid,txt])=>
   `<h3 class=muted style="font-size:.85rem">node ${esc(node).slice(0,8)} · pid ${esc(pid)}</h3><pre>${esc(txt)}</pre>`
  ).join("")).join("")||"<p class=muted>none</p>";}
 return "";
}
document.getElementById("tabs").innerHTML=TABS.map(t=>
 `<button id="tab-${t}" onclick="tab='${t}';sync();render()">${t.replace(/_/g," ")}</button>`).join("");
function sync(){for(const t of TABS)document.getElementById("tab-"+t).className=t===tab?"on":""}
sync();render();
setInterval(()=>{if(document.getElementById("auto").checked)render()},3000);
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet by default
        pass

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        try:
            if self.path in ("/", "/index.html"):
                body = _INDEX.encode()
                ctype = "text/html; charset=utf-8"
            elif self.path == "/metrics":
                from ray_tpu.util import metrics as um

                body = um.prometheus_text().encode()
                ctype = "text/plain; version=0.0.4"
            else:
                data = _payload(self.path)
                if data is None:
                    self.send_error(404)
                    return
                body = json.dumps(data, default=str).encode()
                ctype = "application/json"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except BrokenPipeError:
            pass
        except Exception as e:  # surface handler bugs as 500s, not hangs
            try:
                self.send_error(500, str(e))
            except Exception:
                pass


def start(host: str = "127.0.0.1", port: Optional[int] = None) -> str:
    """Start the dashboard server (idempotent). Returns its URL.

    Default port comes from the ``dashboard_port`` config flag (8265, like
    the reference); ``port=0`` picks a free port (the URL reports it)."""
    if port is None:
        from ray_tpu._private.config import GLOBAL_CONFIG

        port = GLOBAL_CONFIG.dashboard_port
    global _server, _thread
    if _server is not None:
        h, p = _server.server_address[:2]
        return f"http://{h}:{p}"
    _server = ThreadingHTTPServer((host, port), _Handler)
    _server.daemon_threads = True
    _thread = threading.Thread(target=_server.serve_forever, name="dashboard", daemon=True)
    _thread.start()
    h, p = _server.server_address[:2]
    return f"http://{h}:{p}"


def stop() -> None:
    global _server, _thread
    if _server is not None:
        _server.shutdown()
        _server.server_close()
        _server = None
        _thread = None
