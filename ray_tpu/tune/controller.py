"""TuneController: the trial event loop.

Reference: ``python/ray/tune/execution/tune_controller.py:72`` (``step``
:709): launch trial actors up to the concurrency budget, consume reported
results, route them through the scheduler (CONTINUE/STOP/EXPLOIT), commit
checkpoints, checkpoint experiment state, finalize.

Trial execution reuses the train worker machinery: a trial is one
``RayTrainWorker`` actor running the trainable in a ``_TrainSession`` whose
``report``/``get_checkpoint`` are the same functions used under
``ray_tpu.train`` (the reference unified these APIs the same way).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._checkpoint_manager import CheckpointManager
from ray_tpu.train._config import CheckpointConfig, FailureConfig
from ray_tpu.train._session import TrainContext
from ray_tpu.train._worker_group import RayTrainWorker
from ray_tpu.tune import schedulers as sched_mod

PENDING, RUNNING, TERMINATED, ERROR = "PENDING", "RUNNING", "TERMINATED", "ERROR"


class Trial:
    def __init__(
        self,
        idx: int,
        config: dict,
        exp_dir: str,
        ckpt_config: CheckpointConfig,
        trial_id: Optional[str] = None,
        storage=None,  # experiment-level StorageContext (cloud persistence)
    ):
        self.id = trial_id or f"{idx:05d}_{uuid.uuid4().hex[:6]}"
        self.idx = idx
        self.config = config
        self.state = PENDING
        self.last_result: Optional[dict] = None
        self.results: list[dict] = []
        self.error: Optional[BaseException] = None
        self.actor = None
        self.iteration = 0
        self.retries_left = 0
        self.dir = os.path.join(exp_dir, f"trial_{self.id}")
        os.makedirs(self.dir, exist_ok=True)
        trial_storage = storage.for_trial(f"trial_{self.id}") if storage else None
        self.ckpt_manager = CheckpointManager(
            self.dir, ckpt_config, storage=trial_storage
        )
        self.start_checkpoint: Optional[Checkpoint] = None
        self._rungs_hit: set = set()

    @property
    def checkpoint(self) -> Optional[Checkpoint]:
        # start_checkpoint is an injected restore point (PBT exploit) that
        # outranks older own commits; it is cleared on the next own commit
        return self.start_checkpoint or self.ckpt_manager.latest()


class TuneController:
    def __init__(
        self,
        trainable: Callable,
        configs: list[dict],
        exp_dir: str,
        *,
        scheduler=None,
        metric: Optional[str] = None,
        mode: str = "min",
        max_concurrent: int = 8,
        resources_per_trial: Optional[dict[str, float]] = None,
        failure_config: Optional[FailureConfig] = None,
        checkpoint_config: Optional[CheckpointConfig] = None,
        verbose: int = 0,
        searcher=None,
        num_samples: int = 0,
        storage=None,  # StorageContext: checkpoints + experiment state ride pyarrow.fs
    ):
        self.trainable = trainable
        self.exp_dir = exp_dir
        self.storage = storage
        self._last_state_upload = float("-inf")
        os.makedirs(exp_dir, exist_ok=True)
        self.scheduler = scheduler or sched_mod.FIFOScheduler()
        self.metric, self.mode = metric, mode
        self.max_concurrent = max_concurrent
        self.resources = resources_per_trial or {"CPU": 1.0}
        self.failure_config = failure_config or FailureConfig()
        ckpt_config = checkpoint_config or CheckpointConfig()
        self.verbose = verbose
        self._ckpt_config = ckpt_config
        # sequential-searcher mode: trials are pulled from searcher.suggest
        # lazily as slots free up (reference: SearchGenerator); batch mode:
        # the pre-expanded config list.
        self.searcher = searcher
        self.num_samples = num_samples
        self._searcher_done = searcher is None
        self.trials = [
            Trial(i, c, exp_dir, ckpt_config, storage=storage)
            for i, c in enumerate(configs)
        ]
        for t in self.trials:
            t.retries_left = self.failure_config.max_failures

    # ------------------------------------------------------------------ loop

    def run(self) -> list[Trial]:
        try:
            while (
                any(t.state in (PENDING, RUNNING) for t in self.trials)
                or not self._searcher_done
            ):
                self._pull_suggestions()
                self._launch_pending()
                progressed = self._poll_running()
                if not progressed:
                    time.sleep(0.02)
            return self.trials
        finally:
            for t in self.trials:
                self._stop_actor(t)
            self._save_experiment_state(force=True)  # final state must land

    def _pull_suggestions(self):
        """Ask the sequential searcher for new trials while slots are free."""
        if self._searcher_done:
            return
        from ray_tpu.tune.searcher import FINISHED

        active = sum(1 for t in self.trials if t.state in (PENDING, RUNNING))
        while len(self.trials) < self.num_samples and active < self.max_concurrent:
            idx = len(self.trials)
            trial_id = f"{idx:05d}_{uuid.uuid4().hex[:6]}"
            out = self.searcher.suggest(trial_id)
            if out is None:
                return  # searcher wants to wait for completions
            if out == FINISHED:
                self._searcher_done = True
                self.num_samples = len(self.trials)
                return
            trial = Trial(
                idx, out, self.exp_dir, self._ckpt_config,
                trial_id=trial_id, storage=self.storage,
            )
            trial.retries_left = self.failure_config.max_failures
            self.trials.append(trial)
            active += 1
        if len(self.trials) >= self.num_samples:
            self._searcher_done = True

    def _notify_searcher_complete(self, trial: Trial, error: bool):
        if self.searcher is not None:
            try:
                self.searcher.on_trial_complete(
                    trial.id, result=trial.last_result, error=error
                )
            except Exception:
                pass

    def _launch_pending(self):
        running = sum(1 for t in self.trials if t.state == RUNNING)
        for t in self.trials:
            if running >= self.max_concurrent:
                return
            if t.state == PENDING:
                self._start_trial(t)
                running += 1

    def _start_trial(self, trial: Trial, checkpoint: Optional[Checkpoint] = None):
        cls = ray_tpu.remote(num_cpus=0)(RayTrainWorker)
        trial.actor = cls.options(resources=dict(self.resources)).remote()
        ctx = TrainContext(
            world_size=1, world_rank=0, local_rank=0, local_world_size=1, node_rank=0,
            experiment_name=os.path.basename(self.exp_dir),
            trial_name=f"trial_{trial.id}", trial_id=trial.id,
        )
        ckpt = checkpoint if checkpoint is not None else trial.checkpoint
        if checkpoint is not None:
            # remember an externally-injected restore point (PBT exploit) so a
            # crash before the trial's first own commit retries from it
            trial.start_checkpoint = checkpoint
        trial.actor.start_training.remote(self.trainable, trial.config, ctx, ckpt, None)
        trial.state = RUNNING
        if self.verbose:
            print(f"[tune] trial {trial.id} started config={trial.config}")

    def _stop_actor(self, trial: Trial):
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None

    def _poll_running(self) -> bool:
        progressed = False
        # fire all polls first so the 50ms waits overlap instead of serializing
        running = [t for t in self.trials if t.state == RUNNING]
        futures = [(t, t.actor.next_result.remote(0.05)) for t in running]
        for trial, fut in futures:
            if trial.state != RUNNING:
                continue  # stopped by a decision earlier in this round
            try:
                ev = ray_tpu.get(fut, timeout=30.0)
            except Exception as e:
                self._on_trial_failure(trial, e)
                progressed = True
                continue
            if ev is None:
                continue
            progressed = True
            kind = ev[0]
            if kind == "result":
                self._on_result(trial, ev[1], ev[2])
            elif kind == "done":
                trial.state = TERMINATED
                self._stop_actor(trial)
                self._notify_searcher_complete(trial, error=False)
                self._save_experiment_state()
            elif kind == "error":
                self._on_trial_failure(trial, ev[1])
        return progressed

    def _on_result(self, trial: Trial, metrics: dict, reported_ckpt):
        trial.iteration += 1
        metrics = dict(metrics)
        metrics.setdefault("training_iteration", trial.iteration)
        metrics.setdefault("trial_id", trial.id)
        trial.last_result = metrics
        trial.results.append(metrics)
        if self.searcher is not None:
            try:
                self.searcher.on_trial_result(trial.id, metrics)
            except Exception:
                pass
        if reported_ckpt is not None:
            trial.ckpt_manager.commit(reported_ckpt, metrics)
            trial.start_checkpoint = None  # own commit supersedes any override
        decision = self.scheduler.on_result(trial, metrics)
        if decision == sched_mod.STOP:
            # ack first so the session thread isn't stuck in report() when the
            # process dies
            self._ack(trial)
            trial.state = TERMINATED
            self._stop_actor(trial)
            self._notify_searcher_complete(trial, error=False)
            if self.verbose:
                print(f"[tune] trial {trial.id} early-stopped at iter {trial.iteration}")
        elif decision == sched_mod.EXPLOIT:
            donor = self.scheduler.choose_exploit_source(trial, self.trials)
            if donor is not None and donor.checkpoint is not None:
                self._exploit(trial, donor)
            else:
                self._ack(trial)
        else:
            self._ack(trial)
        self._save_experiment_state()

    def _ack(self, trial: Trial):
        if trial.actor is not None:
            try:
                ray_tpu.get(trial.actor.ack_result.remote(), timeout=10.0)
            except Exception:
                pass

    def _exploit(self, trial: Trial, donor: Trial):
        """PBT exploit+explore (reference ``pbt.py:865``): restart this trial
        from the donor's checkpoint with a perturbed copy of donor's config."""
        self._stop_actor(trial)
        new_config = dict(donor.config)
        if hasattr(self.scheduler, "perturb_config"):
            new_config = self.scheduler.perturb_config(new_config)
        trial.config = new_config
        donor_ckpt = donor.checkpoint
        if self.verbose:
            print(f"[tune] trial {trial.id} exploits {donor.id}; new config={new_config}")
        self._start_trial(trial, checkpoint=donor_ckpt)

    def _on_trial_failure(self, trial: Trial, error: BaseException):
        self._stop_actor(trial)
        if trial.retries_left != 0:
            if trial.retries_left > 0:
                trial.retries_left -= 1
            trial.state = PENDING  # relaunched from latest checkpoint
            if self.verbose:
                print(f"[tune] trial {trial.id} failed ({error}); will retry")
        else:
            trial.state = ERROR
            trial.error = error
            self._notify_searcher_complete(trial, error=True)
        self._save_experiment_state()

    # ------------------------------------------------------- state snapshot

    def _save_experiment_state(self, force: bool = False):
        """Experiment-state checkpoint (reference ``tune_controller.py:451``
        periodic experiment snapshots). The local JSON is cheap and written
        every call; the cloud upload is throttled unless ``force``."""
        state = {
            "timestamp": time.time(),
            "trials": [
                {
                    "id": t.id,
                    "config": _json_safe(t.config),
                    "state": t.state,
                    "last_result": _json_safe(t.last_result or {}),
                    "iteration": t.iteration,
                    "dir": t.dir,
                    "error": repr(t.error) if t.error else None,
                }
                for t in self.trials
            ],
        }
        tmp = os.path.join(self.exp_dir, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, os.path.join(self.exp_dir, "experiment_state.json"))
        if self.storage is not None and (
            force or time.monotonic() - self._last_state_upload >= 10.0
        ):
            # experiment state rides the same pyarrow.fs tier as checkpoints,
            # PERIODICALLY — a blocking cloud PUT per trial result would
            # serialize the whole control loop behind uploads (reference:
            # tune_controller.py:451 periodic cloud snapshots)
            try:
                self.storage.write_json("experiment_state.json", state)
                self._last_state_upload = time.monotonic()
            except Exception as e:  # noqa: BLE001 - storage outage must not kill the loop
                print(f"[ray_tpu.tune] experiment-state upload failed: {e!r}")


from ray_tpu.train._checkpoint_manager import json_safe as _json_safe  # noqa: E402
