"""Searcher plugin API + a bundled TPE searcher.

Reference: ``python/ray/tune/search/searcher.py`` (``Searcher.suggest`` /
``on_trial_complete`` — the interface Optuna/HyperOpt/Ax plug into) and
``search/concurrency_limiter.py``. Sequential searchers see every completed
trial before proposing the next config, unlike ``BasicVariantGenerator``
which pre-expands the whole grid up front; the TuneController pulls
suggestions lazily as concurrency slots free up.

``TPESearcher`` is the bundled non-trivial example: a per-dimension
Tree-structured Parzen Estimator (Bergstra et al. 2011, the algorithm behind
HyperOpt) — observations are split into good/bad by quantile, candidates are
drawn from a KDE over the good set and ranked by the good/bad density ratio.
"""

from __future__ import annotations

import math
import random
from typing import Any, Optional

from ray_tpu.tune.search import Categorical, Domain, Float, GridSearch, Integer, _set_path, _walk

FINISHED = "FINISHED"  # sentinel: searcher is done proposing


class Searcher:
    """Subclass and implement ``suggest``/``on_trial_complete``."""

    def __init__(self, metric: Optional[str] = None, mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode  # None = inherit from TuneConfig at fit time
        self._space: Optional[dict] = None

    def set_search_properties(self, metric: Optional[str], mode: Optional[str], space: dict) -> None:
        # constructor args always win — TuneConfig only fills gaps (its mode
        # DEFAULT of "min" must never override an explicit searcher mode)
        self.metric = self.metric or metric
        if self.mode is None:
            self.mode = mode
        self._space = space

    @property
    def resolved_mode(self) -> str:
        return self.mode or "min"

    def suggest(self, trial_id: str) -> Optional[dict]:
        """A config for this trial; None = wait; FINISHED = no more trials."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass

    def on_trial_complete(
        self, trial_id: str, result: Optional[dict] = None, error: bool = False
    ) -> None:
        pass


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference: ConcurrencyLimiter)."""

    def __init__(self, searcher: Searcher, max_concurrent: int = 4):
        super().__init__(searcher.metric, searcher.mode)  # None passes through
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def set_search_properties(self, metric, mode, space):
        super().set_search_properties(metric, mode, space)
        self.searcher.set_search_properties(metric, mode, space)

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return None
        out = self.searcher.suggest(trial_id)
        if isinstance(out, dict):
            self._live.add(trial_id)
        return out

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class RandomSearcher(Searcher):
    """Pure-prior sampling through the Searcher interface (baseline)."""

    def __init__(self, metric=None, mode=None, seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.rng = random.Random(seed)

    def suggest(self, trial_id: str):
        cfg: dict = {}
        for path, v in _walk(self._space or {}):
            if isinstance(v, Domain):
                _set_path(cfg, path, v.sample(self.rng))
            elif isinstance(v, (GridSearch, dict)):
                raise ValueError("grid_search is not supported by sequential searchers")
            else:
                _set_path(cfg, path, v)
        return cfg


class TPESearcher(Searcher):
    """Independent per-dimension TPE.

    good/bad split at the ``gamma`` quantile of observed scores; Float and
    Integer dims use a Gaussian KDE over the good set (bandwidth shrinking
    with #observations), Categorical dims a smoothed count ratio. The first
    ``n_initial`` suggestions sample the prior.
    """

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        n_initial: int = 8,
        gamma: float = 0.25,
        n_candidates: int = 24,
        seed: Optional[int] = None,
    ):
        super().__init__(metric, mode)
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._obs: list[tuple[dict, float]] = []   # (flat config, score)
        self._pending: dict[str, dict] = {}

    # -- observation feed --------------------------------------------------

    def on_trial_complete(self, trial_id, result=None, error=False):
        flat = self._pending.pop(trial_id, None)
        if flat is None or error or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.resolved_mode == "max":
            score = -score
        self._obs.append((flat, score))

    # -- suggestion --------------------------------------------------------

    def suggest(self, trial_id: str):
        if self._space is None:
            raise RuntimeError("set_search_properties was never called")
        leaves = list(_walk(self._space))
        flat: dict[tuple, Any] = {}
        cfg: dict = {}
        use_tpe = len(self._obs) >= self.n_initial
        good, bad = self._split() if use_tpe else ([], [])
        for path, v in leaves:
            if isinstance(v, Domain):
                if use_tpe:
                    val = self._suggest_dim(path, v, good, bad)
                else:
                    val = v.sample(self.rng)
                flat[path] = val
                _set_path(cfg, path, val)
            elif isinstance(v, (GridSearch, dict)) and (
                isinstance(v, GridSearch) or "grid_search" in v
            ):
                raise ValueError("grid_search is not supported by TPESearcher")
            else:
                _set_path(cfg, path, v)
        self._pending[trial_id] = flat
        return cfg

    def _split(self):
        ranked = sorted(self._obs, key=lambda o: o[1])
        n_good = max(1, math.ceil(self.gamma * len(ranked)))
        return ranked[:n_good], ranked[n_good:]

    def _suggest_dim(self, path, domain: Domain, good, bad):
        gvals = [o[0][path] for o in good if path in o[0]]
        bvals = [o[0][path] for o in bad if path in o[0]]
        if not gvals:
            return domain.sample(self.rng)
        if isinstance(domain, Categorical):
            return self._categorical(domain, gvals, bvals)
        if isinstance(domain, (Float, Integer)):
            lo = float(domain.lower)
            hi = float(domain.upper)
            log = isinstance(domain, Float) and domain.log
            tx = math.log if log else (lambda x: float(x))
            inv = math.exp if log else (lambda x: x)
            val = self._numeric(tx(lo), tx(hi), [tx(v) for v in gvals], [tx(v) for v in bvals])
            val = inv(val)
            if isinstance(domain, Integer):
                val = min(domain.upper - 1, max(domain.lower, int(round(val))))
            else:
                val = min(hi, max(lo, val))
            return val
        return domain.sample(self.rng)

    def _numeric(self, lo, hi, gvals, bvals):
        width = max(hi - lo, 1e-12)
        bw = max(width / max(math.sqrt(len(gvals)), 1.0), 1e-3 * width)

        def logpdf(x, vals):
            if not vals:
                return -math.log(width)  # uniform fallback
            acc = 0.0
            for m in vals:
                acc += math.exp(-0.5 * ((x - m) / bw) ** 2)
            return math.log(max(acc / (len(vals) * bw * math.sqrt(2 * math.pi)), 1e-300))

        best, best_score = None, -math.inf
        for _ in range(self.n_candidates):
            m = self.rng.choice(gvals)
            x = min(hi, max(lo, self.rng.gauss(m, bw)))
            score = logpdf(x, gvals) - logpdf(x, bvals)
            if score > best_score:
                best, best_score = x, score
        return best

    def _categorical(self, domain: Categorical, gvals, bvals):
        def probs(vals):
            counts = {c: 1.0 for c in domain.categories}  # +1 smoothing
            for v in vals:
                counts[v] = counts.get(v, 1.0) + 1.0
            total = sum(counts.values())
            return {c: counts[c] / total for c in domain.categories}

        pg, pb = probs(gvals), probs(bvals)
        ratio = {c: pg[c] / pb[c] for c in domain.categories}
        cands = [self._weighted_choice(pg) for _ in range(self.n_candidates)]
        return max(cands, key=lambda c: ratio[c])

    def _weighted_choice(self, p: dict):
        r = self.rng.random()
        acc = 0.0
        for c, w in p.items():
            acc += w
            if r <= acc:
                return c
        return next(iter(p))
