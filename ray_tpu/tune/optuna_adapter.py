"""Optuna adapter for the Searcher plugin API.

Reference: ``python/ray/tune/search/optuna/optuna_search.py`` — the reference
drives Optuna through its ask/tell interface (``OptunaSearch.suggest`` ->
``study.ask``, ``on_trial_complete`` -> ``study.tell``), translating Tune
sample domains into Optuna distributions. Same shape here, against our
``ray_tpu.tune.search`` domains.

Optuna is an optional dependency: importing this module is safe without it;
constructing ``OptunaSearcher`` raises ImportError with install guidance.
Only final values reach Optuna (``on_trial_complete`` -> ``study.tell``);
Optuna pruners are not wired — our schedulers own early stopping, matching
the division of labor in the reference.
"""

from __future__ import annotations

import random
from typing import Any, Optional

from ray_tpu.tune.search import (
    Categorical,
    Domain,
    Float,
    GridSearch,
    Integer,
    Quantized,
    _set_path,
    _walk,
)
from ray_tpu.tune.searcher import Searcher


def _optuna():
    try:
        import optuna
    except ImportError as e:  # pragma: no cover - exercised only without optuna
        raise ImportError(
            "OptunaSearcher requires `optuna`. It is not bundled with ray_tpu; "
            "install it in the driver environment (pip install optuna)."
        ) from e
    return optuna


class OptunaSearcher(Searcher):
    """Sequential searcher backed by an Optuna study (TPE by default).

    ``sampler`` accepts any ``optuna.samplers.BaseSampler``; ``seed`` seeds
    the default TPESampler. Nested search-space paths are flattened to
    ``a/b/c`` parameter names for Optuna and unflattened on the way out.
    """

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        sampler: Any = None,
        seed: Optional[int] = None,
    ):
        super().__init__(metric, mode)
        self._optuna = _optuna()
        self._sampler = sampler
        self._seed = seed
        self._study = None
        self._trials: dict[str, Any] = {}  # our trial_id -> optuna trial
        self._rng = random.Random(seed)

    def _ensure_study(self):
        if self._study is None:
            opt = self._optuna
            sampler = self._sampler or opt.samplers.TPESampler(seed=self._seed)
            direction = "maximize" if self.resolved_mode == "max" else "minimize"
            opt.logging.set_verbosity(opt.logging.WARNING)
            self._study = opt.create_study(direction=direction, sampler=sampler)
        return self._study

    # -- domain translation -------------------------------------------------

    def _distributions(self):
        """(flat-name -> optuna distribution, passthrough leaves)."""
        opt = self._optuna
        dists: dict[str, Any] = {}
        passthrough: list[tuple[tuple, Any]] = []
        for path, v in _walk(self._space or {}):
            name = "/".join(path)
            if isinstance(v, Float):
                dists[name] = opt.distributions.FloatDistribution(v.lower, v.upper, log=v.log)
            elif isinstance(v, Quantized) and isinstance(v.inner, Float) and not v.inner.log:
                # optuna forbids log=True together with step; log-quantized
                # domains fall through to passthrough sampling below
                dists[name] = opt.distributions.FloatDistribution(
                    v.inner.lower, v.inner.upper, step=v.q
                )
            elif isinstance(v, Integer):
                # our Integer samples randrange(lower, upper) — exclusive upper;
                # optuna's IntDistribution is inclusive
                dists[name] = opt.distributions.IntDistribution(v.lower, v.upper - 1)
            elif isinstance(v, Categorical):
                dists[name] = opt.distributions.CategoricalDistribution(v.categories)
            elif isinstance(v, GridSearch) or (isinstance(v, dict) and "grid_search" in v):
                raise ValueError("grid_search is not supported by OptunaSearcher")
            else:
                # constants, sample_from, and any Domain optuna can't model
                # (sampled from our own prior, outside the study)
                passthrough.append((path, v))
        return dists, passthrough

    # -- Searcher interface --------------------------------------------------

    def suggest(self, trial_id: str):
        if self._space is None:
            raise RuntimeError("set_search_properties was never called")
        study = self._ensure_study()
        dists, passthrough = self._distributions()
        ot = study.ask(dists)
        self._trials[trial_id] = ot
        cfg: dict = {}
        for name, val in ot.params.items():
            _set_path(cfg, tuple(name.split("/")), val)
        for path, v in passthrough:
            _set_path(cfg, path, v.sample(self._rng) if isinstance(v, Domain) else v)
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        opt = self._optuna
        study = self._ensure_study()
        if error or not result or self.metric not in result:
            study.tell(ot, state=opt.trial.TrialState.FAIL)
        else:
            study.tell(ot, float(result[self.metric]))
