"""Trial schedulers: FIFO, ASHA (async successive halving), Median stopping,
and Population Based Training.

Reference: ``python/ray/tune/schedulers/`` — ``AsyncHyperBandScheduler``
(``async_hyperband.py``), ``MedianStoppingRule``, ``PopulationBasedTraining``
(``pbt.py:221``, ``_exploit`` :865). Decisions are made per reported result:
CONTINUE / STOP / and for PBT, EXPLOIT (clone a better trial's checkpoint +
perturbed config).
"""

from __future__ import annotations

import math
import random
from typing import Any, Callable, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"


class FIFOScheduler:
    def on_result(self, trial, result: dict) -> str:
        return CONTINUE

    def choose_exploit_source(self, trial, trials):
        return None


class AsyncHyperBandScheduler:
    """ASHA: at rungs t = grace_period * reduction_factor^k, stop trials whose
    metric falls below the top-1/reduction_factor quantile of completed rung
    records."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        max_t: int = 100,
        reduction_factor: float = 4.0,
    ):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self.grace_period, self.max_t, self.rf = grace_period, max_t, reduction_factor
        # rung value -> list of recorded metric values
        self.rungs: dict[int, list[float]] = {}
        r = grace_period
        while r < max_t:
            self.rungs[int(r)] = []
            r *= reduction_factor

    def on_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr)
        v = result.get(self.metric)
        if t is None or v is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        decision = CONTINUE
        for rung in sorted(self.rungs, reverse=True):
            if t < rung:
                continue
            recorded = self.rungs[rung]
            if rung not in getattr(trial, "_rungs_hit", set()):
                trial._rungs_hit = getattr(trial, "_rungs_hit", set()) | {rung}
                recorded.append(float(v))
            if len(recorded) >= self.rf:
                cutoff = self._cutoff(recorded)
                if cutoff is not None and self._worse(float(v), cutoff):
                    decision = STOP
            break
        return decision

    def _cutoff(self, recorded: list[float]) -> Optional[float]:
        if not recorded:
            return None
        srt = sorted(recorded, reverse=(self.mode == "max"))
        k = max(1, int(len(srt) / self.rf))
        return srt[k - 1]

    def _worse(self, v: float, cutoff: float) -> bool:
        return v > cutoff if self.mode == "min" else v < cutoff

    def choose_exploit_source(self, trial, trials):
        return None


# ASHA is the common alias
ASHAScheduler = AsyncHyperBandScheduler


class MedianStoppingRule:
    """Stop a trial whose best metric is worse than the median of other
    trials' running averages at the same time step."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.histories: dict[Any, list[float]] = {}

    def on_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        v = result.get(self.metric)
        if v is None:
            return CONTINUE
        self.histories.setdefault(trial.id, []).append(float(v))
        if t < self.grace_period or len(self.histories) < self.min_samples:
            return CONTINUE
        means = [
            sum(h) / len(h) for tid, h in self.histories.items() if tid != trial.id and h
        ]
        if len(means) < self.min_samples - 1:
            return CONTINUE
        med = sorted(means)[len(means) // 2]
        mine = self.histories[trial.id]
        best = min(mine) if self.mode == "min" else max(mine)
        if (self.mode == "min" and best > med) or (self.mode == "max" and best < med):
            return STOP
        return CONTINUE

    def choose_exploit_source(self, trial, trials):
        return None


class PopulationBasedTraining:
    """PBT (reference ``schedulers/pbt.py:221``): every
    ``perturbation_interval`` steps, bottom-quantile trials clone a top-
    quantile trial's checkpoint and continue with a perturbed config."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        perturbation_interval: int = 2,
        hyperparam_mutations: Optional[dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.rng = random.Random(seed)
        self.last_perturb: dict[Any, int] = {}
        self.latest: dict[Any, float] = {}

    def on_result(self, trial, result: dict) -> str:
        v = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if v is not None:
            self.latest[trial.id] = float(v)
        last = self.last_perturb.get(trial.id, 0)
        if t - last >= self.interval:
            self.last_perturb[trial.id] = t
            return EXPLOIT
        return CONTINUE

    def choose_exploit_source(self, trial, trials):
        """If ``trial`` is in the bottom quantile, pick a top-quantile donor;
        else None (keep going)."""
        scored = [(tid, s) for tid, s in self.latest.items()]
        if len(scored) < 2:
            return None
        reverse = self.mode == "max"
        ranked = sorted(scored, key=lambda kv: kv[1], reverse=reverse)
        n = len(ranked)
        k = max(1, int(math.ceil(n * self.quantile)))
        top = [tid for tid, _ in ranked[:k]]
        bottom = {tid for tid, _ in ranked[-k:]}
        if trial.id not in bottom or trial.id in top:
            return None
        donor_id = self.rng.choice(top)
        if donor_id == trial.id:
            return None
        for t in trials:
            if t.id == donor_id:
                return t
        return None

    def perturb_config(self, config: dict) -> dict:
        """Explore step: multiply floats by 0.8/1.2 or resample
        (reference pbt.py explore)."""
        out = dict(config)
        for key, spec in self.mutations.items():
            if key not in out:
                continue
            if self.rng.random() < self.resample_p:
                out[key] = self._resample(spec)
            else:
                cur = out[key]
                if isinstance(cur, (int, float)) and not isinstance(cur, bool):
                    factor = self.rng.choice([0.8, 1.2])
                    out[key] = type(cur)(cur * factor) if isinstance(cur, float) else max(1, int(cur * factor))
                else:
                    out[key] = self._resample(spec)
        return out

    def _resample(self, spec):
        from ray_tpu.tune.search import Domain

        if isinstance(spec, Domain):
            return spec.sample(self.rng)
        if callable(spec):
            return spec()
        if isinstance(spec, (list, tuple)):
            return self.rng.choice(list(spec))
        return spec
