"""Trainable registry: resolve string names passed to Tuner/tune.run.

Reference: ``python/ray/tune/registry.py`` (``register_trainable``, RLlib
algorithms resolvable by name, e.g. ``tune.run("PPO")``).
"""

from __future__ import annotations

from typing import Callable, Union

_TRAINABLES: dict[str, Callable] = {}


def register_trainable(name: str, trainable: Callable) -> None:
    _TRAINABLES[name] = trainable


def resolve_trainable(trainable: Union[str, Callable]) -> Callable:
    if not isinstance(trainable, str):
        return trainable
    if trainable in _TRAINABLES:
        return _TRAINABLES[trainable]
    # RL algorithms are resolvable by name, reference-style.
    try:
        from ray_tpu.rl import get_algorithm_class

        cls = get_algorithm_class(trainable)
        return cls.as_trainable(cls.get_default_config())
    except KeyError:
        raise KeyError(
            f"Unknown trainable {trainable!r}; registered: {sorted(_TRAINABLES)} "
            "plus RL algorithm names"
        ) from None
