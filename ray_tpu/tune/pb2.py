"""PB2: Population Based Bandits — PBT with a GP-UCB explore step.

Reference: ``python/ray/tune/schedulers/pb2.py`` (+ ``pb2_utils.py``) — the
reference fits a time-varying GP to (hyperparams -> reward delta) data and
selects the exploit target's new config by maximizing UCB, instead of PBT's
random 0.8x/1.2x multiply (Parker-Holder et al. 2020, "Provably Efficient
Online Hyperparameter Optimization with Population-Based Bandits").

Departure from the reference: the reference wraps GPy; here the GP is exact
and hand-rolled on numpy (RBF kernel, median-heuristic lengthscale,
standardized targets). Population sizes make N = trials x intervals tiny
(tens), so the O(N^3) solve is microseconds and needs no dependency. The
time-varying kernel is approximated by exponentially down-weighting old
observations in the noise term rather than the reference's full TV kernel —
same effect (stale windows count less) with a fraction of the machinery.

Data model: one observation per (trial, perturbation window) — x = the
hyperparameters the trial ran with during the window (normalized to [0,1]
within ``hyperparam_bounds``), y = the improvement in ``metric`` across the
window (sign-adjusted so larger is always better). ``perturb_config`` then
maximizes UCB over candidates drawn in bounds.
"""

from __future__ import annotations

import math
import random
from typing import Any, Optional

from ray_tpu.tune.schedulers import PopulationBasedTraining

_MIN_OBS_FOR_GP = 4  # below this, fall back to PBT-style random perturbation


class PB2(PopulationBasedTraining):
    """Drop-in PBT replacement: same exploit policy, bandit-driven explore.

    ``hyperparam_bounds`` maps config key -> (lower, upper); only these keys
    are optimized (others ride along unchanged). Keys whose bounds span
    >= 2 decades with a positive lower bound are modeled in log space —
    matching how learning rates are actually tuned.
    """

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        perturbation_interval: int = 2,
        hyperparam_bounds: Optional[dict[str, tuple[float, float]]] = None,
        quantile_fraction: float = 0.25,
        ucb_kappa: float = 1.5,
        n_candidates: int = 64,
        forget: float = 0.9,
        seed: Optional[int] = None,
    ):
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds={key: (lo, hi)}")
        super().__init__(
            metric=metric,
            mode=mode,
            time_attr=time_attr,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations={},
            quantile_fraction=quantile_fraction,
            seed=seed,
        )
        self.bounds = {k: (float(lo), float(hi)) for k, (lo, hi) in hyperparam_bounds.items()}
        self.keys = sorted(self.bounds)
        self._log_key = {
            k: (lo > 0 and hi / lo >= 100.0) for k, (lo, hi) in self.bounds.items()
        }
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        self.forget = forget
        # (x in [0,1]^d, improvement, age counter at insert)
        self._X: list[list[float]] = []
        self._y: list[float] = []
        self._epoch = 0  # bumps every recorded window; drives forgetting
        self._ages: list[int] = []
        # per-trial open window: (t_start, metric_start, x vector)
        self._window: dict[Any, tuple[float, float, list[float]]] = {}

    # -- normalization -----------------------------------------------------

    def _encode(self, config: dict) -> list[float]:
        x = []
        for k in self.keys:
            lo, hi = self.bounds[k]
            v = float(config.get(k, lo))
            if self._log_key[k]:
                lo_t, hi_t, v_t = math.log(lo), math.log(hi), math.log(max(v, 1e-300))
            else:
                lo_t, hi_t, v_t = lo, hi, v
            x.append(min(1.0, max(0.0, (v_t - lo_t) / max(hi_t - lo_t, 1e-12))))
        return x

    def _decode(self, x: list[float]) -> dict:
        out = {}
        for k, u in zip(self.keys, x):
            lo, hi = self.bounds[k]
            if self._log_key[k]:
                v = math.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))
            else:
                v = lo + u * (hi - lo)
            out[k] = v
        return out

    # -- observation collection --------------------------------------------

    def on_result(self, trial, result: dict) -> str:
        decision = super().on_result(trial, result)
        v = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if v is not None:
            score = float(v) if self.mode == "max" else -float(v)  # larger = better
            win = self._window.get(trial.id)
            exploiting = decision not in (None, "CONTINUE")
            if win is None:
                self._window[trial.id] = (t, score, self._encode(trial.config))
            else:
                t0, s0, x0 = win
                # close the window at a full interval OR at an exploit
                # boundary (PBT fires EXPLOIT every `interval` steps, which
                # is one report EARLIER than t - t0 >= interval can trigger
                # for a window opened the report after the last exploit —
                # without this clause the GP never receives data)
                if (exploiting or t - t0 >= self.interval) and t > t0:
                    self._X.append(x0)
                    self._y.append((score - s0) / (t - t0))  # improvement rate
                    self._ages.append(self._epoch)
                    self._epoch += 1
                    self._window[trial.id] = (t, score, self._encode(trial.config))
            if exploiting:
                # an EXPLOIT may clone another trial's state+config; an open
                # window would straddle the clone and poison the GP data —
                # drop it and let the next report open a fresh one
                self._window.pop(trial.id, None)
        return decision

    # -- explore step -------------------------------------------------------

    def perturb_config(self, config: dict) -> dict:
        import numpy as np  # deferred: `import ray_tpu.tune` must not need numpy

        out = dict(config)
        if len(self._y) < _MIN_OBS_FOR_GP:
            # PBT-style fallback: gaussian jitter in normalized space
            out.update(self._decode([
                min(1.0, max(0.0, u + self.rng.gauss(0.0, 0.2)))
                for u in self._encode(out)
            ]))
            return out
        X = np.asarray(self._X, dtype=np.float64)
        y = np.asarray(self._y, dtype=np.float64)
        ages = np.asarray(self._ages, dtype=np.float64)
        # standardize targets; exponential forgetting inflates old-sample noise
        y_mu, y_sd = float(y.mean()), float(y.std()) or 1.0
        ys = (y - y_mu) / y_sd
        staleness = (self._epoch - 1) - ages
        noise = 1e-2 / np.maximum(self.forget ** staleness, 1e-3)

        d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        med = float(np.median(d2[d2 > 0])) if (d2 > 0).any() else 1.0
        ls2 = max(med, 1e-6)
        K = np.exp(-0.5 * d2 / ls2) + np.diag(noise)
        try:
            L = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            L = np.linalg.cholesky(K + 1e-6 * np.eye(len(K)))
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, ys))

        cur = np.asarray(self._encode(out), dtype=np.float64)
        cands = [cur]
        for _ in range(self.n_candidates):
            if self.rng.random() < 0.5:  # local jitter around current
                c = np.clip(cur + np.array([self.rng.gauss(0, 0.15) for _ in self.keys]), 0, 1)
            else:  # global draw
                c = np.array([self.rng.random() for _ in self.keys])
            cands.append(c)
        C = np.stack(cands)
        kx = np.exp(-0.5 * ((C[:, None, :] - X[None, :, :]) ** 2).sum(-1) / ls2)
        mean = kx @ alpha
        v = np.linalg.solve(L, kx.T)
        var = np.maximum(1.0 - (v * v).sum(0), 1e-12)
        ucb = mean + self.kappa * np.sqrt(var)
        best = C[int(np.argmax(ucb))]
        out.update(self._decode([float(u) for u in best]))
        return out
