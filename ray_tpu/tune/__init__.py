"""ray_tpu.tune — hyperparameter search over trial actors.

Reference surface (``python/ray/tune/``): ``Tuner.fit`` (``tuner.py:347``) /
``tune.run`` (``tune.py:233``) driving a controller event loop
(``execution/tune_controller.py``); search spaces; schedulers (ASHA, PBT,
median-stopping); per-trial checkpointing; experiment state snapshots.

``tune.report`` / ``tune.get_checkpoint`` are the same session functions as
``ray_tpu.train`` — a trainable is a train loop with one implicit worker.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Optional

from ray_tpu.train._checkpoint import Checkpoint  # noqa: F401
from ray_tpu.train._config import CheckpointConfig, FailureConfig, RunConfig
from ray_tpu.train._session import get_checkpoint, get_context, report  # noqa: F401
from ray_tpu.train.trainer import Result
from ray_tpu.tune.controller import ERROR, TERMINATED, TuneController
from ray_tpu.tune.registry import register_trainable, resolve_trainable  # noqa: F401
from ray_tpu.tune.search import (  # noqa: F401
    BasicVariantGenerator,
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.searcher import (  # noqa: F401
    ConcurrencyLimiter,
    RandomSearcher,
    Searcher,
    TPESearcher,
)
from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.pb2 import PB2  # noqa: F401

# OptunaSearcher lives in ray_tpu.tune.optuna_adapter; not imported eagerly
# here so `import ray_tpu.tune` never requires optuna.


@dataclasses.dataclass
class TuneConfig:
    """Reference: ``tune/tune_config.py``."""

    metric: Optional[str] = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 8
    scheduler: Any = None
    search_alg: Any = None
    seed: Optional[int] = None


class ResultGrid:
    """Reference: ``tune/result_grid.py``."""

    def __init__(self, results: list[Result], metric=None, mode="min"):
        self._results = results
        self._metric, self._mode = metric, mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self):
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None, mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("pass metric= (or set TuneConfig.metric)")
        scored = [r for r in self._results if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        pick = min if mode == "min" else max
        return pick(scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics for r in self._results if r.metrics])


class Tuner:
    """Reference: ``tune/tuner.py:347``."""

    def __init__(
        self,
        trainable: Callable,
        *,
        param_space: Optional[dict] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        if isinstance(trainable, str):  # "PPO" etc. (reference: tune registry)
            from ray_tpu.tune.registry import resolve_trainable

            trainable = resolve_trainable(trainable)
        resources = getattr(trainable, "_tune_resources", None)
        if hasattr(trainable, "as_trainable"):  # a Trainer instance
            trainable = trainable.as_trainable()
            if resources is not None:  # carry with_resources() across the wrap
                trainable._tune_resources = resources
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def fit(self) -> ResultGrid:
        from ray_tpu.train import _storage as storage_mod
        from ray_tpu.train._storage import StorageContext

        cfg = self.tune_config
        name = self.run_config.name or f"tune_{int(time.time())}"
        storage_path = self.run_config.resolved_storage_path()
        storage_fs = self.run_config.storage_filesystem
        if storage_fs is not None or storage_mod.is_uri(storage_path):
            # cloud/URI persistence via pyarrow.fs: trial dirs stay local
            # staging, checkpoints + experiment state ride the StorageContext
            storage = StorageContext(storage_path, name, storage_filesystem=storage_fs)
            exp_dir = os.path.join(
                os.path.expanduser("~/ray_tpu_results"), "_staging", name
            )
        else:
            storage = None
            exp_dir = os.path.join(storage_path, name)
        searcher = None
        configs: list[dict] = []
        if cfg.search_alg is not None and hasattr(cfg.search_alg, "suggest"):
            # sequential Searcher plugin (reference: search_alg=OptunaSearch())
            searcher = cfg.search_alg
            searcher.set_search_properties(cfg.metric, cfg.mode, self.param_space)
        else:
            gen = cfg.search_alg or BasicVariantGenerator(seed=cfg.seed)
            configs = gen.generate(self.param_space, num_samples=cfg.num_samples)
        resources = getattr(self.trainable, "_tune_resources", None)
        controller = TuneController(
            self.trainable,
            configs,
            exp_dir,
            scheduler=cfg.scheduler,
            metric=cfg.metric,
            mode=cfg.mode,
            max_concurrent=cfg.max_concurrent_trials,
            resources_per_trial=resources,
            failure_config=self.run_config.failure_config,
            checkpoint_config=self.run_config.checkpoint_config,
            verbose=self.run_config.verbose > 1,
            searcher=searcher,
            num_samples=cfg.num_samples,
            storage=storage,
        )
        trials = controller.run()
        results = []
        for t in trials:
            # with cloud storage, point users at the durable location — the
            # staging dir is throwaway and dies with the head
            t_storage = t.ckpt_manager.storage
            results.append(
                Result(
                    metrics=t.last_result,
                    checkpoint=t.ckpt_manager.best(),
                    path=t_storage.uri_for("") if t_storage is not None else t.dir,
                    error=t.error,
                    metrics_history=t.results,
                )
            )
        return ResultGrid(results, metric=cfg.metric, mode=cfg.mode)


def with_resources(trainable: Callable, resources: dict[str, float]) -> Callable:
    """Attach per-trial resources (reference ``tune.with_resources``)."""
    trainable._tune_resources = dict(resources)
    return trainable


def run(
    trainable: Callable,
    *,
    config: Optional[dict] = None,
    num_samples: int = 1,
    metric: Optional[str] = None,
    mode: str = "min",
    scheduler=None,
    storage_path: Optional[str] = None,
    name: Optional[str] = None,
    max_concurrent_trials: int = 8,
    verbose: int = 1,
) -> ResultGrid:
    """Classic ``tune.run`` API (reference ``tune/tune.py:233``)."""
    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric,
            mode=mode,
            num_samples=num_samples,
            scheduler=scheduler,
            max_concurrent_trials=max_concurrent_trials,
        ),
        run_config=RunConfig(name=name, storage_path=storage_path, verbose=verbose),
    )
    return tuner.fit()
