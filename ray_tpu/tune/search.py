"""Search spaces + variant generation.

Reference: ``python/ray/tune/search/`` — sample domains
(``tune/search/sample.py``), ``BasicVariantGenerator``
(``search/basic_variant.py``) expanding ``grid_search`` specs and sampling
stochastic domains, with ``num_samples`` repetitions.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        import math

        if self.log:
            return math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


class Quantized(Domain):
    def __init__(self, inner: Domain, q: float):
        self.inner, self.q = inner, q

    def sample(self, rng):
        return round(self.inner.sample(rng) / self.q) * self.q


def quniform(lower: float, upper: float, q: float) -> Quantized:
    return Quantized(Float(lower, upper), q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def choice(categories) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values) -> dict:
    return {"grid_search": list(values)}


def _walk(space: dict, prefix=()):
    """Yield (path, value) leaves of a nested param space."""
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, dict) and "grid_search" not in v:
            yield from _walk(v, path)
        else:
            yield path, v


def _set_path(d: dict, path: tuple, value):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


class BasicVariantGenerator:
    """Grid × random expansion (reference ``search/basic_variant.py``)."""

    def __init__(self, seed: Optional[int] = None):
        self.rng = random.Random(seed)

    def generate(self, space: dict, num_samples: int = 1) -> list[dict]:
        leaves = list(_walk(space or {}))
        grid_leaves = []
        grid_values = []
        for path, v in leaves:
            if isinstance(v, dict) and "grid_search" in v:
                grid_leaves.append(path)
                grid_values.append(v["grid_search"])
            elif isinstance(v, GridSearch):
                grid_leaves.append(path)
                grid_values.append(v.values)
        configs = []
        grid_combos = list(itertools.product(*grid_values)) if grid_values else [()]
        for _ in range(num_samples):
            for combo in grid_combos:
                cfg: dict = {}
                for path, v in leaves:
                    if isinstance(v, Domain):
                        _set_path(cfg, path, v.sample(self.rng))
                    elif isinstance(v, GridSearch) or (isinstance(v, dict) and "grid_search" in v):
                        pass  # filled from the grid combo below
                    else:
                        _set_path(cfg, path, v)
                for path, val in zip(grid_leaves, combo):
                    _set_path(cfg, path, val)
                configs.append(cfg)
        return configs
