"""Binary IDs for objects, tasks, actors, nodes, placement groups.

TPU-native counterpart of the reference's ``src/ray/common/id.h`` (28-byte
TaskID/ObjectID with embedded owner+index). We keep the essential properties —
globally unique, cheaply hashable, order-stamped so an object id encodes its
producing task and return index — with a simpler 16-byte layout since our
control plane is centralized rather than fully decentralized.
"""

from __future__ import annotations

import os
import threading

_ID_SIZE = 16


class BaseID:
    __slots__ = ("_bin",)
    _kind = "ID"

    def __init__(self, binary: bytes):
        if len(binary) != _ID_SIZE:
            raise ValueError(f"{self._kind} must be {_ID_SIZE} bytes, got {len(binary)}")
        self._bin = binary

    @classmethod
    def from_random(cls):
        return cls(os.urandom(_ID_SIZE))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _ID_SIZE)

    def is_nil(self) -> bool:
        return self._bin == b"\x00" * _ID_SIZE

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._bin.hex()

    def __hash__(self):
        return hash(self._bin)

    def __eq__(self, other):
        return type(other) is type(self) and other._bin == self._bin

    def __repr__(self):
        return f"{self._kind}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bin,))


class TaskID(BaseID):
    _kind = "TaskID"


class ActorID(BaseID):
    _kind = "ActorID"


class NodeID(BaseID):
    _kind = "NodeID"


class JobID(BaseID):
    _kind = "JobID"


class PlacementGroupID(BaseID):
    _kind = "PlacementGroupID"


class ObjectID(BaseID):
    """Object ids embed (task id prefix, return index) like the reference's
    ObjectID::FromIndex (id.h), so lineage can map an object back to the task
    that produced it."""

    _kind = "ObjectID"

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        if not 0 <= index < (1 << 32):
            raise ValueError("return index out of range")
        return cls(task_id.binary()[:12] + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls) -> "ObjectID":
        return cls.from_random()

    def task_prefix(self) -> bytes:
        return self._bin[:12]

    def return_index(self) -> int:
        return int.from_bytes(self._bin[12:], "little")


class _Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._v += 1
            return self._v
