"""Randomized fault injection for stress/chaos testing.

Reference: ``python/ray/_private/test_utils.py:1396,1464``
(ResourceKillerActor / NodeKillerActor randomly SIGKILL worker and raylet
processes while workloads run) and ``python/ray/tests/test_chaos.py``. The
round-3 GC deadlock was exactly the class of bug that per-feature tests miss
and randomized pressure finds — this module is product code (not buried in a
test helper) so any deployment can soak-test its own workloads.

The killer runs inside the driver process of an in-process head (the test
topology) and SIGKILLs random live worker subprocesses; the head's existing
failure machinery — conn-EOF death detection, task retries, actor restart
FSM, lineage reconstruction — must absorb every kill.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Optional


class ResourceKiller:
    """Periodically SIGKILL a random live worker while a workload runs.

    Seeded for reproducibility (a failing seed is a regression test). Use as
    a context manager::

        with ResourceKiller(interval_s=0.4, seed=7):
            run_workload()
    """

    def __init__(
        self,
        interval_s: float = 0.5,
        seed: int = 0,
        warmup_s: float = 0.3,
        max_kills: Optional[int] = None,
        kill_actor_workers: bool = True,
    ):
        self.interval_s = interval_s
        self.rng = random.Random(seed)
        self.warmup_s = warmup_s
        self.max_kills = max_kills
        self.kill_actor_workers = kill_actor_workers
        self.kills: list[tuple[float, int, str]] = []  # (t, pid, kind)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- targets -----------------------------------------------------------
    def _candidates(self):
        from ray_tpu._private.runtime import get_ctx

        head = getattr(get_ctx(), "head", None)
        if head is None:
            raise RuntimeError("ResourceKiller needs an in-process head (driver)")
        out = []
        with head.lock:
            for node in head.nodes.values():
                for wh in node.all_workers:
                    if not wh.alive or wh.proc is None or not wh.proc.is_alive():
                        continue
                    if wh.actor_id is not None and not self.kill_actor_workers:
                        continue
                    out.append(wh)
        return out

    def _kill_one(self) -> bool:
        victims = self._candidates()
        if not victims:
            return False
        wh = self.rng.choice(victims)
        kind = "actor-worker" if wh.actor_id is not None else "task-worker"
        pid = wh.proc.pid
        try:
            os.kill(pid, signal.SIGKILL)  # brutal, like the reference
        except (ProcessLookupError, OSError):
            return False
        self.kills.append((time.monotonic(), pid, kind))
        return True

    # -- lifecycle ---------------------------------------------------------
    def _run(self):
        time.sleep(self.warmup_s)
        while not self._stop.is_set():
            if self.max_kills is not None and len(self.kills) >= self.max_kills:
                return
            try:
                self._kill_one()
            except Exception:  # noqa: BLE001
                # the runtime may be torn down (shutdown() mid-chaos) while
                # this thread is live — that is "no candidates", not a crash
                return
            self._stop.wait(self.interval_s)

    def start(self) -> "ResourceKiller":
        self._thread = threading.Thread(
            target=self._run, name="resource-killer", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> list[tuple[float, int, str]]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        return self.kills

    def __enter__(self) -> "ResourceKiller":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


# ---------------------------------------------------------------------------
# serve-plane chaos: replica and controller killers
# ---------------------------------------------------------------------------


def _workers_by_actor_id(actor_ids: set[bytes]):
    """Live worker handles whose actor is one of ``actor_ids``."""
    from ray_tpu._private.runtime import get_ctx

    head = getattr(get_ctx(), "head", None)
    if head is None:
        raise RuntimeError("serve chaos needs an in-process head (driver)")
    out = []
    with head.lock:
        for node in head.nodes.values():
            for wh in node.all_workers:
                if not wh.alive or wh.proc is None or not wh.proc.is_alive():
                    continue
                if wh.actor_id in actor_ids:
                    out.append(wh)
    return out


def pid_of_actor(actor_id_hex: str):
    """PID of the worker hosting an actor (None when not found/alive) —
    lets a test SIGKILL a SPECIFIC serve replica deterministically."""
    whs = _workers_by_actor_id({bytes.fromhex(actor_id_hex)})
    return whs[0].proc.pid if whs else None


def kill_serve_controller() -> Optional[int]:
    """SIGKILL the serve controller's worker process; returns the pid (None
    when no controller is running). The data plane — proxies, routers,
    replicas, in-flight streams — must keep serving without it; only
    control-plane actions (deploy, autoscale, replica replacement) pause
    until a new controller is started (``serve.run`` recreates it)."""
    from ray_tpu._private.runtime import get_ctx
    from ray_tpu.serve._private.common import CONTROLLER_NAME

    head = getattr(get_ctx(), "head", None)
    if head is None:
        raise RuntimeError("serve chaos needs an in-process head (driver)")
    with head.lock:
        # named_actors is keyed "<namespace>:<name>"; the detached
        # controller registers under whichever namespace created it
        aid = next(
            (
                v for k, v in head.named_actors.items()
                if k.rsplit(":", 1)[-1] == CONTROLLER_NAME
            ),
            None,
        )
    if aid is None:
        return None
    whs = _workers_by_actor_id({aid})
    if not whs:
        return None
    pid = whs[0].proc.pid
    try:
        os.kill(pid, signal.SIGKILL)
    except (ProcessLookupError, OSError):
        return None
    return pid


class ServeReplicaKiller(ResourceKiller):
    """Periodically SIGKILL a random live serve REPLICA while streaming
    traffic runs — the serve-plane analog of ResourceKiller. Every kill
    must be absorbed by mid-stream failover (resumable streams,
    RESILIENCE.md) and the controller's replica replacement; a truncated,
    wrong, or hung stream is a bug.

        with ServeReplicaKiller(deployment="llm_LLMDeployment",
                                interval_s=1.0, seed=7):
            run_streaming_workload()

    ``deployment=None`` targets every deployment's replicas.
    """

    def __init__(
        self,
        deployment: Optional[str] = None,
        interval_s: float = 1.0,
        seed: int = 0,
        warmup_s: float = 0.3,
        max_kills: Optional[int] = None,
    ):
        super().__init__(
            interval_s=interval_s, seed=seed, warmup_s=warmup_s,
            max_kills=max_kills,
        )
        self.deployment = deployment

    def _candidates(self):
        import ray_tpu
        from ray_tpu._private.log_util import warn_throttled
        from ray_tpu.serve._private.common import CONTROLLER_NAME

        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            by_dep = ray_tpu.get(
                controller.get_replica_actor_ids.remote(self.deployment),
                timeout=10,
            )
        except Exception as e:
            # transient by design: the controller may itself be mid-kill /
            # mid-restart in a combined chaos scenario. "No candidates this
            # tick" keeps the killer thread alive (the base _run treats an
            # ESCAPING exception as runtime teardown and stops for good,
            # which would silently end chaos injection mid-soak).
            warn_throttled("serve chaos: controller lookup", e)
            return []
        ids = {
            bytes.fromhex(h) for hs in by_dep.values() for h in hs
        }
        return _workers_by_actor_id(ids)

    def _kill_one(self) -> bool:
        victims = self._candidates()
        if not victims:
            return False
        wh = self.rng.choice(victims)
        pid = wh.proc.pid
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, OSError):
            return False
        self.kills.append((time.monotonic(), pid, "serve-replica"))
        return True
