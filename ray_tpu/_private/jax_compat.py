"""Version shims over moving jax APIs.

The repo targets the modern surface (``jax.shard_map`` with
``check_vma``); older installs (0.4.x) only ship
``jax.experimental.shard_map.shard_map`` with the same semantics under
``check_rep``. Kernel/parallel call sites import from here so the rest
of the codebase stays on one spelling.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` when available, else the 0.4.x experimental one
    (``check_vma`` maps onto the old ``check_rep`` flag)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def axis_size(axis_name) -> int:
    """Static mesh-axis size inside an SPMD region. ``jax.lax.axis_size``
    when available; on 0.4.x ``psum(1, axis)`` constant-folds to a Python
    int at trace time, so loop bounds stay static either way."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
