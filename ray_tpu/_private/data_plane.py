"""Peer-to-peer bulk object transfer (the data plane).

The reference moves objects node→node directly: each raylet's object manager
serves chunks over gRPC with push/pull managers
(``src/ray/object_manager/object_manager.h:117``, ``push_manager.h:30``,
``pull_manager.cc:48``) and the GCS holds only the directory. Round 2 of
this build funneled every remote byte through the head as one inline RPC —
two hops, head bandwidth = cluster bandwidth. This module is the fix:

* every host (head and node agents) runs a ``DataServer`` — an
  hmac-authenticated TCP listener that serves the host's shared-memory
  objects (arena blocks pinned for the duration of the send; dedicated
  segments attached read-only) in bounded chunks, zero-copy out of the
  mapping via ``send_bytes(memoryview)``;
* consumers ``fetch()`` straight from the owning host — the head hands out
  only the locator (object directory role) and its data socket address;
* receivers write into one preallocated buffer via ``recv_bytes_into``
  (single copy off the socket), then deserialize with out-of-band buffer
  views into it (no further copies).

Connections are pooled per address and reused; a vanished object (freed or
spilled between locator and fetch) answers ("gone", reason) and the caller
falls back to the head's restore path, mirroring the reference's pull-retry.

Known limitation (vs the reference's per-raylet spill): agent hosts do not
spill to disk. The arena is bounded by a watermark — workers degrade to the
head-mediated inline path (whose spill machinery applies) when their arena
passes 90% — but over-arena-cap dedicated segments are bounded only by
object lifetimes (the head frees them promptly, and agents sweep orphans by
name prefix at shutdown).
"""

from __future__ import annotations

import threading
import time
from multiprocessing.connection import Client, Listener
from typing import Optional

from ray_tpu._private import events

#: flight-recorder events this module emits (raylint RL012 registry): the
#: serving half of a cross-host pull (``role="serve"``; the consumer half
#: is emitted by runtime._fetch_via_data_plane).
EVENT_NAMES = ("core.object.p2p_pull",)


def _chunk_bytes() -> int:
    from ray_tpu._private.config import GLOBAL_CONFIG

    # floor, not validation error: a zero/negative override would make the
    # sender's while loop emit empty messages forever
    return max(4096, GLOBAL_CONFIG.object_transfer_chunk_bytes)


class DataServer:
    """Serves this host's shm objects to remote pullers."""

    def __init__(self, authkey: bytes, host: str = "0.0.0.0"):
        self._listener = Listener((host, 0), authkey=authkey)
        self.port = self._listener.address[1]
        self.bytes_served = 0
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="data-server", daemon=True
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except Exception:  # noqa: BLE001 - auth failures, fd exhaustion
                if self._shutdown:
                    return
                # don't hot-spin on a persistent accept error (e.g. EMFILE)
                time.sleep(0.05)
                continue
            threading.Thread(
                target=self._serve, args=(conn,), name="data-serve", daemon=True
            ).start()

    def _serve(self, conn) -> None:
        from ray_tpu._private.shm_store import ShmReader

        try:
            while True:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                if msg[0] == "stat":
                    # liveness probe: does this host still hold the object?
                    # (head uses it to verify a report_lost before freeing)
                    try:
                        ShmReader(msg[1]).close()
                        conn.send(("ok_stat", True))
                    except FileNotFoundError:
                        conn.send(("ok_stat", False))
                    continue
                if msg[0] != "fetch":
                    conn.send(("err", f"unknown request {msg[0]!r}"))
                    continue
                loc = msg[1]
                try:
                    reader = ShmReader(loc)
                except FileNotFoundError as e:
                    conn.send(("gone", str(e)))
                    continue
                try:
                    mv = reader._mv()
                    total = loc.total_size
                    conn.send(("ok", total))
                    off = 0
                    chunk = _chunk_bytes()
                    while off < total:
                        n = min(chunk, total - off)
                        conn.send_bytes(mv[off : off + n])
                        off += n
                    self.bytes_served += total
                    events.emit(
                        "core.object.p2p_pull",
                        size=total,
                        seg=loc.name,
                        role="serve",
                    )
                finally:
                    reader.close()
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self._listener.close()
        except OSError:
            pass


class _Pool:
    """Per-address client connection pool (one cached conn per (addr, thread)
    would over-connect; a small free-list with a lock is plenty — fetches are
    bulk transfers, not latency-bound RPCs)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._free: dict[tuple, list] = {}

    def take(self, address: tuple, authkey: bytes):
        with self._lock:
            conns = self._free.get(address)
            if conns:
                return conns.pop()
        return Client(address, authkey=authkey)

    def give(self, address: tuple, conn) -> None:
        with self._lock:
            self._free.setdefault(address, []).append(conn)

    def clear(self) -> None:
        with self._lock:
            for conns in self._free.values():
                for c in conns:
                    try:
                        c.close()
                    except OSError:
                        pass
            self._free.clear()


_pool = _Pool()


class ObjectGone(Exception):
    """The owning host no longer has the object (freed/spilled/evicted)."""


def fetch(address: tuple[str, int], authkey: bytes, loc) -> memoryview:
    """Pull one object's laid-out bytes from its owning host.

    Returns a memoryview over a freshly received buffer in the shm layout
    ([header][buf0][buf1...], see shm_store._layout) — deserialize with
    ``read_layout``. Raises ObjectGone when the owner dropped it, OSError
    when the host is unreachable.
    """
    conn = _pool.take(address, authkey)
    ok = False
    try:
        conn.send(("fetch", loc))
        resp = conn.recv()
        if resp[0] == "gone":
            ok = True  # connection still healthy — pool it
            raise ObjectGone(resp[1])
        if resp[0] == "err":
            # the server's explicit error reply (unknown request): the
            # connection is still healthy and carries the reason — name
            # the kind instead of folding it into the catch-all below
            # (raylint RL019: every sent kind has a named handler)
            ok = True
            raise OSError(f"data server error: {resp[1]}")
        if resp[0] != "ok":
            raise OSError(f"data server error: {resp!r}")
        total = resp[1]
        buf = bytearray(total)
        mv = memoryview(buf)
        off = 0
        while off < total:
            n = conn.recv_bytes_into(mv[off:])
            off += n
        ok = True
        return mv
    finally:
        if ok:
            _pool.give(address, conn)
        else:
            try:
                conn.close()
            except OSError:
                pass


def read_layout(mv: memoryview, loc):
    """Deserialize a value from fetched layout bytes (zero further copies:
    out-of-band buffers are views into ``mv``)."""
    import pickle

    from ray_tpu._private.shm_store import layout_views

    header, bufs = layout_views(mv, loc.header_len, loc.buffer_lens)
    return pickle.loads(header, buffers=bufs)


def stat(address: tuple[str, int], authkey: bytes, loc) -> Optional[bool]:
    """Ask the owning host whether it still holds ``loc``. True/False from
    the server; None when the host is unreachable (let node-death handling
    decide — do NOT treat unreachable as gone)."""
    try:
        conn = _pool.take(address, authkey)
    except OSError:
        return None
    ok = False
    try:
        conn.send(("stat", loc))
        resp = conn.recv()
        ok = True
        return bool(resp[1]) if resp[0] == "ok_stat" else None
    except (OSError, EOFError):
        return None
    finally:
        if ok:
            _pool.give(address, conn)
        else:
            try:
                conn.close()
            except OSError:
                pass


def shutdown_pool() -> None:
    _pool.clear()
